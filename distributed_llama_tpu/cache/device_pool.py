"""Device-resident paged KV: block-pool metadata + the radix *directory*.

The vLLM-style refactor (docs/PAGED_KV.md): instead of one contiguous
(L, B, hk, S, hs) cache row per slot, KV lives in a device-resident POOL of
fixed-size blocks — (L, N, hk, block_tokens, hs) per side — and each slot
carries a BLOCK TABLE mapping virtual positions [0, seq_len) to pool blocks
(position p lives in block table[p // bt] at offset p % bt). The arrays
themselves stay on the Engine (they are donated through every dispatch like
the dense caches were); this module owns only the HOST metadata:

- `DeviceKVPool` — refcounts + free list over the N block ids. Block 0 is a
  permanent SCRATCH block: idle rows park their masked garbage writes there
  and unpopulated table entries point at it, so a dispatch never needs a
  "no block" sentinel. A block with refcount 1 is exclusively owned by its
  holder and may be written; refcount > 1 means shared (a slot appending
  into a shared block must copy-on-write first — the engine does the device
  copy, this module just answers `shared()`).

- `PagedPrefixCache` — the host-side radix index re-cast as a *directory*
  over device blocks: a node's handle is a ("dev", block_id) reference (one
  pool refcount held per node), so a prefix hit is a refcounted block-table
  REMAP — zero bytes moved — and a finished slot's harvest is an incref,
  not a copy. Under pool pressure, LRU unreferenced directory nodes DEMOTE
  their blocks device→host into the existing `cache/block_pool.KVBlockPool`
  (the same hot/Q80 tier + LRU the host prefix cache already had — one
  unified spill path, docs/PAGED_KV.md "Eviction"); a later hit on a
  ("cold", handle) node pays one host→device upload and promotes back.

Locking: `DeviceKVPool` has its own lock (alloc/free/refs are touched from
the scheduler thread and close()); the directory keeps the PrefixCache
convention of one lock over tree + tier state.
"""

from __future__ import annotations

import threading

import numpy as np

from ..obs import metrics
from .radix import RadixIndex, RadixNode

__all__ = ["DeviceKVPool", "PagedPrefixCache", "PagedLease",
           "KVPoolExhausted", "SCRATCH_BLOCK"]

SCRATCH_BLOCK = 0  # permanent garbage target; never allocated, never read

_POOL_BLOCKS = metrics.gauge(
    "paged_kv_pool_blocks", "Device KV pool capacity in blocks (--kv-pool-blocks)")
_POOL_FREE = metrics.gauge(
    "paged_kv_free_blocks", "Device KV pool blocks currently unallocated")
_REMAPPED = metrics.counter(
    "paged_kv_remapped_blocks_total",
    "Directory blocks remapped into a slot's table at admission "
    "(zero-copy prefix reuse — no KV bytes moved)")
_COW = metrics.counter(
    "paged_kv_cow_blocks_total",
    "Copy-on-write block duplications (a slot about to append into a "
    "shared block gets a private device-side copy)")
_DEMOTED = metrics.counter(
    "paged_kv_demoted_blocks_total",
    "Directory blocks demoted device->host under pool pressure (into the "
    "unified cache/block_pool.py tier)")
_PROMOTED = metrics.counter(
    "paged_kv_promoted_blocks_total",
    "Cold directory blocks promoted host->device on a prefix hit")
_SEED_BYTES = metrics.counter(
    "paged_kv_seed_bytes_total",
    "KV bytes moved host->device at admission seeding (0 for device-tier "
    "hits — the zero-copy remap claim, asserted by the shared-prefix bench; "
    "nonzero only when a cold block is promoted)")


class KVPoolExhausted(RuntimeError):
    """The device block pool could not serve an allocation even after
    reclaiming the directory and idle slots. Attributable to the request
    whose growth needed the blocks: the scheduler fails only it."""

    fault_scope = "request"


class DeviceKVPool:
    """Refcount + free-list metadata for the device block pool. The arrays
    live on the Engine; `n_blocks` must match their N axis."""

    def __init__(self, n_blocks: int, block_tokens: int):
        assert n_blocks >= 2, "pool needs the scratch block plus one real block"
        assert block_tokens >= 1
        self.n_blocks = n_blocks
        self.block_tokens = block_tokens
        self._lock = threading.Lock()  # guards: _refs, _free
        self._refs = np.zeros(n_blocks, np.int32)
        self._refs[SCRATCH_BLOCK] = 1  # permanently pinned, never allocatable
        self._free = list(range(n_blocks - 1, 0, -1))  # stack, low ids first out
        _POOL_BLOCKS.set(n_blocks)
        _POOL_FREE.set(len(self._free))

    # ------------------------------------------------------------------

    def alloc(self, n: int) -> list[int] | None:
        """Allocate n blocks (refcount 1 each), all-or-nothing. None when
        fewer than n are free — the caller reclaims and retries."""
        with self._lock:
            if len(self._free) < n:
                return None
            ids = [self._free.pop() for _ in range(n)]
            for b in ids:
                assert self._refs[b] == 0, (b, int(self._refs[b]))
                self._refs[b] = 1
            _POOL_FREE.set(len(self._free))
            return ids

    def incref(self, ids) -> None:
        with self._lock:
            for b in ids:
                assert self._refs[b] > 0, f"incref on free block {b}"
                self._refs[b] += 1

    def decref(self, ids) -> int:
        """Drop one reference per id; blocks reaching zero return to the
        free list. Returns how many were freed."""
        freed = 0
        with self._lock:
            for b in ids:
                assert b != SCRATCH_BLOCK and self._refs[b] > 0, (
                    b, int(self._refs[b]))
                self._refs[b] -= 1
                if self._refs[b] == 0:
                    self._free.append(b)
                    freed += 1
            _POOL_FREE.set(len(self._free))
        return freed

    def shared(self, bid: int) -> bool:
        """True when more than one holder references the block — a slot must
        copy-on-write before appending into it."""
        with self._lock:
            return int(self._refs[bid]) > 1

    def free_blocks(self) -> int:
        with self._lock:
            return len(self._free)

    def used_blocks(self) -> int:
        with self._lock:
            return self.n_blocks - 1 - len(self._free)

    def reset(self) -> None:
        """Drop every allocation (engine re-initialization: the device
        arrays were rebuilt, nothing references the old blocks)."""
        with self._lock:
            self._refs[:] = 0
            self._refs[SCRATCH_BLOCK] = 1
            self._free = list(range(self.n_blocks - 1, 0, -1))
            _POOL_FREE.set(len(self._free))

    def refcounts(self) -> np.ndarray:
        """Snapshot for tests/stats."""
        with self._lock:
            return self._refs.copy()

    def note_cow(self) -> None:
        _COW.inc()

    def stats(self) -> dict:
        with self._lock:
            free = len(self._free)
        return {"pool_blocks": self.n_blocks, "free_blocks": free,
                "block_tokens": self.block_tokens}


class PagedLease:
    """Refcount pin on the directory chain a request was admitted against
    (the paged analog of prefix_cache.PrefixLease — same lifecycle:
    mark_seeded/mark_unused + release, shrink on history truncation)."""

    __slots__ = ("nodes", "tokens")

    def __init__(self, nodes: list[RadixNode], tokens: int):
        self.nodes = nodes
        self.tokens = tokens


class PagedPrefixCache:
    """Radix directory over device blocks + unified host cold tier.

    Node handles are ("dev", block_id) — one DeviceKVPool reference held per
    node — or ("cold", host_handle) into `cold` (a cache/block_pool.py
    KVBlockPool: the existing host hot/Q80 tier, now the ONE demotion target
    for paged eviction). The public surface mirrors PrefixCache so the
    scheduler, /v1/stats and the benches keep one vocabulary."""

    def __init__(self, pool: DeviceKVPool, block_tokens: int,
                 cold_blocks: int = 0, q80: bool = False):
        from .block_pool import KVBlockPool

        self.pool = pool
        self.block_tokens = block_tokens
        self.radix = RadixIndex(block_tokens)
        self.cold = (KVBlockPool(cold_blocks, q80=q80)
                     if cold_blocks > 0 else None)
        self._lock = threading.Lock()  # guards: radix, hits, misses, unused_hits, hit_tokens, resident_tokens, evicted_blocks, demoted, promoted, prompt_tokens
        self.hits = 0
        self.misses = 0
        self.unused_hits = 0
        self.hit_tokens = 0
        self.resident_tokens = 0
        self.evicted_blocks = 0
        self.demoted = 0
        self.promoted = 0
        self.prompt_tokens = 0

    # ------------------------------------------------------------------
    # lookup / lease lifecycle (PrefixCache-compatible)
    # ------------------------------------------------------------------

    def lookup(self, prompt: list[int], cap: int | None = None
               ) -> PagedLease | None:
        """Longest directory block-prefix of `prompt` as an acquired lease —
        same reuse caps as PrefixCache.lookup (len-1, caller cap). No data
        is touched: the engine resolves each node's tier when it adopts the
        chain into a slot table."""
        with self._lock:
            self.prompt_tokens += len(prompt)
            nodes = self.radix.match(prompt)
            n = len(nodes) * self.block_tokens
            n = min(n, len(prompt) - 1)
            if cap is not None:
                n = min(n, cap)
            if n < 1:
                self.misses += 1
                from .prefix_cache import _MISSES

                _MISSES.inc()
                return None
            nodes = nodes[:(n + self.block_tokens - 1) // self.block_tokens]
            self.radix.acquire(nodes)
        return PagedLease(nodes, n)

    def mark_seeded(self, lease: PagedLease, used_tokens: int) -> None:
        from .prefix_cache import _HIT_TOKENS, _HITS

        with self._lock:
            self.hits += 1
            self.hit_tokens += used_tokens
        _HITS.inc()
        _HIT_TOKENS.inc(used_tokens)

    def note_resident(self, tokens: int) -> None:
        if tokens <= 0:
            return
        from .prefix_cache import _RESIDENT_TOKENS

        with self._lock:
            self.resident_tokens += tokens
        _RESIDENT_TOKENS.inc(tokens)

    def mark_unused(self, lease: PagedLease | None) -> None:
        if lease is None:
            return
        from .prefix_cache import _UNUSED

        with self._lock:
            self.unused_hits += 1
        _UNUSED.inc()
        self.release(lease)

    def release(self, lease: PagedLease | None) -> None:
        if lease is None:
            return
        with self._lock:
            nodes, lease.nodes = lease.nodes, []
            lease.tokens = 0
            if nodes:
                self.radix.release(nodes)

    def shrink(self, lease: PagedLease, n_tokens: int) -> None:
        if n_tokens >= lease.tokens:
            return
        keep = (max(n_tokens, 0) + self.block_tokens - 1) // self.block_tokens
        with self._lock:
            drop, lease.nodes = lease.nodes[keep:], lease.nodes[:keep]
            lease.tokens = max(n_tokens, 0)
            if drop:
                self.radix.release(drop)

    # ------------------------------------------------------------------
    # directory mutation
    # ------------------------------------------------------------------

    def insert_blocks(self, tokens: list[int], block_ids: list[int]) -> int:
        """Attach the slot's committed full blocks to the directory BY
        REFERENCE: node i takes a pool ref on block_ids[i]. No data moves —
        this is the zero-copy harvest. Block positions the tree already
        covers keep their existing blocks (the slot's duplicate is simply
        not referenced and dies with the slot's own table). Returns how many
        new nodes were created."""
        from .prefix_cache import _INSERTED

        bt = self.block_tokens
        n_blocks = min(len(tokens) // bt, len(block_ids))
        if n_blocks == 0:
            return 0
        blocked = tokens[:n_blocks * bt]
        created = 0

        def make_handle(i: int):
            nonlocal created
            self.pool.incref([block_ids[i]])
            created += 1
            return ("dev", block_ids[i])

        with self._lock:
            self.radix.insert(blocked, make_handle)
        _INSERTED.inc(created)
        return created

    def insert_cold(self, tokens: list[int], blocks: list) -> int:
        """Import externally-supplied HOST rows (disaggregation transfer,
        docs/DISAGG.md) as COLD directory nodes: `blocks[i]` is the (k, v)
        host pair for token block i of `tokens`. No device work — the
        existing admission path promotes cold nodes on the first hit, on
        the scheduler thread, so this is safe from any thread. Positions
        the tree already covers keep their existing (possibly device-tier)
        blocks; the supplied copy is simply unused there. A full cold tier
        first evicts its LRU unreferenced subtrees; if it still refuses,
        the chain stops at the last block that fit (prefix-closed by
        construction). Returns how many blocks of `tokens` the directory
        COVERS after the insert (pre-existing nodes count — the importer
        cares about servable span, not authorship)."""
        from .prefix_cache import _INSERTED

        if self.cold is None:
            return 0
        bt = self.block_tokens
        n_blocks = min(len(tokens) // bt, len(blocks))
        if n_blocks == 0:
            return 0
        blocked = tokens[:n_blocks * bt]
        created = 0
        dev_freed: list[int] = []

        def make_handle(i: int):
            nonlocal created
            k, v = blocks[i]
            h = self.cold.put(k, v)
            if h is None:
                dev_freed.extend(self._evict_cold_locked(1))
                h = self.cold.put(k, v)
            if h is None:
                return None  # cold tier pinned full: stop extending
            created += 1
            return ("cold", h)

        with self._lock:
            chain = self.radix.insert(blocked, make_handle)
        if dev_freed:
            # dev-tier descendants dropped with an evicted cold subtree
            # surrender their pool refs (same contract as reclaim())
            self.pool.decref(dev_freed)
        _INSERTED.inc(created)
        return len(chain)

    def promote(self, node: RadixNode, new_bid: int) -> None:
        """A cold node's rows were uploaded into freshly-allocated device
        block `new_bid` (the engine did the transfer): the directory adopts
        the device copy — one tier, one LRU — and frees the host block."""
        with self._lock:
            tier, h = node.handle
            assert tier == "cold", node.handle
            self.pool.incref([new_bid])
            node.handle = ("dev", new_bid)
            if self.cold is not None:
                self.cold.free(h)
            self.promoted += 1
        _PROMOTED.inc()

    def reclaim(self, n_blocks: int, read_block) -> int:
        """Free up to n_blocks device blocks by demoting (or, with no cold
        tier, evicting) LRU UNREFERENCED device-tier nodes. `read_block(bid)
        -> (k, v)` host arrays (L, hk, bt, hs) performs the device→host copy
        for demotion. Returns how many device blocks were released to the
        pool's free list (shared blocks drop the directory's ref but stay
        alive for the slots still holding them)."""
        with self._lock:
            victims = []
            stack = [self.radix.root]
            while stack:
                node = stack.pop()
                stack.extend(node.children.values())
                if (node is not self.radix.root and node.refs == 0
                        and isinstance(node.handle, tuple)
                        and node.handle[0] == "dev"):
                    victims.append(node)
            victims.sort(key=lambda v: v.stamp)
            released = []
            for node in victims:
                if len(released) >= n_blocks:
                    break
                # keep walking past victims that release nothing (a block
                # still shared with a slot's table, or a subtree drop
                # aborted by a lease pin) — slicing the LRU list up front
                # would let reclaimable younger nodes starve an allocation
                # into a spurious KVPoolExhausted
                if node.handle[0] != "dev":
                    continue  # already detached/demoted via an ancestor drop
                bid = node.handle[1]
                if self.cold is not None:
                    try:
                        k, v = read_block(bid)
                        h = self.cold.put(k, v)
                    except Exception:
                        h = None  # demotion is best-effort; evict instead
                    if h is None and len(self.cold) > 0:
                        # cold tier full: evict ITS LRU content first by
                        # dropping the oldest cold-tier nodes outright (any
                        # dev-tier descendants dropped with them surrender
                        # their pool refs through `released` like every
                        # other eviction)
                        released.extend(self._evict_cold_locked(1))
                        if node.handle[0] != "dev":
                            continue  # the victim itself rode out with the
                            # dropped cold subtree (its ref is in released)
                        try:
                            k, v = read_block(bid)
                            h = self.cold.put(k, v)
                        except Exception:
                            h = None
                    if h is not None:
                        node.handle = ("cold", h)
                        self.demoted += 1
                        _DEMOTED.inc()
                        released.append(bid)
                        continue
                # no cold tier (or it refused): evict the node entirely. The
                # node may be mid-chain; prefix closure only constrains the
                # TREE, so drop this node and its whole subtree (descendants
                # without this block are unreachable prefixes anyway).
                released.extend(self._drop_subtree_locked(node))
            freed = 0
        if released:
            freed = self.pool.decref(released)
        return freed

    def _drop_subtree_locked(self, node: RadixNode) -> list[int]:  # holds: self._lock
        """Remove `node` and every descendant from the tree; returns the
        device block ids whose directory refs must be dropped. Descendant
        nodes with refs > 0 (a live lease) abort the drop of that branch —
        the caller simply reclaims less this round."""
        from .prefix_cache import _EVICTED

        stack, doomed = [node], []
        for n in stack:
            stack.extend(n.children.values())
            doomed.append(n)
        if any(n.refs > 0 for n in doomed):
            return []
        del node.parent.children[node.key]
        self.radix.nodes -= len(doomed)
        self.evicted_blocks += len(doomed)
        _EVICTED.inc(len(doomed))
        dev_ids = []
        for n in doomed:
            tier, h = n.handle
            if tier == "dev":
                dev_ids.append(h)
            elif tier == "cold" and self.cold is not None:
                self.cold.free(h)
            n.handle = ("dropped", None)  # a stale victims-list entry must
            # not double-release this block (reclaim skips non-dev handles)
        return dev_ids

    def _evict_cold_locked(self, n: int) -> list[int]:  # holds: self._lock
        """Drop the n LRU unreferenced cold-tier subtrees (frees host pool
        room for an incoming demotion). Returns the DEVICE block ids of any
        dev-tier descendants dropped with them — the caller must decref
        those into the pool, or the blocks leak (their directory refs die
        with the nodes)."""
        cold_nodes = []
        stack = [self.radix.root]
        while stack:
            node = stack.pop()
            stack.extend(node.children.values())
            if (node is not self.radix.root and node.refs == 0
                    and isinstance(node.handle, tuple)
                    and node.handle[0] == "cold"):
                cold_nodes.append(node)
        cold_nodes.sort(key=lambda v: v.stamp)
        dev_ids: list[int] = []
        for node in cold_nodes[:n]:
            if node.handle[0] == "cold":  # not already dropped via ancestor
                dev_ids.extend(self._drop_subtree_locked(node))
        return dev_ids

    def fetch_cold(self, handle: int):
        """Host rows of a cold block (dequantized when Q80) — the upload
        payload for promotion. Outside the lock (Q80 dequantize must not
        stall lookups; the caller's lease pins the node)."""
        assert self.cold is not None
        return self.cold.get(handle)

    def reset(self) -> None:
        """Drop the whole directory (engine re-initialization: the device
        pool was rebuilt, every dev handle is stale)."""
        with self._lock:
            self.radix = RadixIndex(self.block_tokens)
            if self.cold is not None:
                for h in list(self.cold._blocks):
                    self.cold.free(h)

    def total_refs(self) -> int:
        with self._lock:
            return self.radix.total_refs()

    # ------------------------------------------------------------------
    # stats (PrefixCache-compatible keys + paged extras)
    # ------------------------------------------------------------------

    def stats(self) -> dict:
        with self._lock:
            looked = self.hits + self.unused_hits + self.misses
            dev_nodes = 0
            cold_nodes = 0
            stack = [self.radix.root]
            while stack:
                node = stack.pop()
                stack.extend(node.children.values())
                if node is not self.radix.root:
                    if node.handle[0] == "dev":
                        dev_nodes += 1
                    else:
                        cold_nodes += 1
            return {
                "paged": True,
                "hits": self.hits, "misses": self.misses,
                "unused_hits": self.unused_hits,
                "hit_tokens": self.hit_tokens,
                "resident_tokens": self.resident_tokens,
                "prompt_tokens": self.prompt_tokens,
                "hit_rate": (self.hit_tokens / self.prompt_tokens
                             if self.prompt_tokens else 0.0),
                "reuse_rate": ((self.hit_tokens + self.resident_tokens)
                               / self.prompt_tokens
                               if self.prompt_tokens else 0.0),
                "lookup_hit_rate": ((self.hits + self.unused_hits) / looked
                                    if looked else 0.0),
                "evicted_blocks": self.evicted_blocks,
                "demoted_blocks": self.demoted,
                "promoted_blocks": self.promoted,
                "tree_nodes": self.radix.nodes,
                "dev_blocks": dev_nodes, "cold_blocks": cold_nodes,
                "pool_blocks": self.pool.n_blocks,
                "pool_free_blocks": self.pool.free_blocks(),
                "block_tokens": self.block_tokens,
                "q80_tier": self.cold.q80 if self.cold is not None else False,
            }
