"""Shared-prefix KV cache subsystem (docs/PREFIX_CACHE.md).

The cross-request layer between request admission and the device KV cache:

- `radix.py`    — token-block radix index (refcounts, LRU, hit accounting)
- `block_pool.py` — bounded host block store (hot tier + optional Q80 tier)
  + HostKVArena, the one RAM/memmap backend for every host-side KV spill
- `prefix_cache.py` — the facade: lookup/insert/leases/eviction + metrics
- `device_pool.py` — device-resident paged KV (docs/PAGED_KV.md): block
  pool refcounts + the radix DIRECTORY over device blocks (zero-copy
  remap hits, device→host demotion into the KVBlockPool tier)
- `single_slot.py`  — Engine (api_server --batch 1) client, retiring NaiveCache

BatchEngine integrates directly (runtime/batch_engine.py: admission seeding in
`_assign`, harvest in `_finish`).

Submodules are imported lazily (PEP 562): the fleet router (fleet/affinity.py)
reuses the dependency-free radix trie from a process that deliberately loads
no jax and registers no replica-tier metrics — an eager `from .block_pool
import ...` here would drag quants/jax and the prefix_cache_* metric families
into every `cache.radix` importer.
"""

from __future__ import annotations

__all__ = ["DeviceKVPool", "HostKVArena", "KVBlockPool", "PagedPrefixCache",
           "PrefixCache", "PrefixLease", "RadixIndex",
           "SingleSlotCache", "default_pool_blocks", "make_prefix_cache",
           "warn_degraded"]

_LAZY = {"DeviceKVPool": "device_pool", "HostKVArena": "block_pool",
         "KVBlockPool": "block_pool", "PagedPrefixCache": "device_pool",
         "PrefixCache": "prefix_cache",
         "PrefixLease": "prefix_cache", "RadixIndex": "radix",
         "SingleSlotCache": "single_slot"}


def __getattr__(name: str):
    try:
        mod = _LAZY[name]
    except KeyError:
        raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
    from importlib import import_module

    return getattr(import_module(f".{mod}", __name__), name)


def make_prefix_cache(cache_shape, itemsize: int, *, slots: int,
                      prefix_cache=True, blocks: int = 0,
                      block_tokens: int = 16,
                      q80: bool = False) -> PrefixCache | None:
    """The one PrefixCache construction path for every engine entry point
    (BatchEngine and the single-slot ApiState): resolves the enable flag /
    passthrough-instance convention and the auto pool sizing, so the two
    surfaces cannot drift."""
    from .prefix_cache import PrefixCache

    if not prefix_cache:
        return None
    if isinstance(prefix_cache, PrefixCache):
        return prefix_cache
    n = blocks or default_pool_blocks(cache_shape, itemsize, block_tokens,
                                      slots)
    return PrefixCache(max_blocks=n, block_tokens=block_tokens, q80=q80)


def warn_degraded(what: str, exc: Exception) -> None:
    """Uniform stderr warning for cache degradations (seed/insert failures):
    the cache is an optimization, never a correctness gate — callers fall
    back to plain prefill/no-harvest after calling this."""
    import sys

    print(f"⚠️  prefix-cache {what} failed ({type(exc).__name__}: {exc}); "
          "continuing without it", file=sys.stderr)


def default_pool_blocks(cache_shape, itemsize: int, block_tokens: int,
                        slots: int, byte_budget: int = 1 << 30) -> int:
    """Default pool capacity: 4 full contexts per slot set, hard-capped by a
    host byte budget (~1 GiB). The budget wins even when it holds less than
    one full context — a partial-prefix cache (system prompts are usually
    far shorter than seq_len) is still useful, a silent multi-GiB host
    allocation is not. Size explicitly via prefix_cache_blocks for more."""
    n_layers, _b, hk, seq_len, hs = cache_shape
    blocks_per_seq = -(-seq_len // block_tokens)
    block_bytes = 2 * n_layers * hk * block_tokens * hs * itemsize
    cap = max(byte_budget // block_bytes, 1)
    return int(min(4 * max(slots, 1) * blocks_per_seq, cap))
