"""Shared-prefix KV cache facade: radix index + block pool + leases + metrics.

The cross-request layer between admission and the device cache: two concurrent
users sharing a 2k-token system prompt should pay its prefill ONCE. A finished
request's committed prefix is harvested into the block pool (copy-out), and a
new request whose prompt shares a cached block-prefix seeds its slot rows from
the pool (copy-in) so prefill runs only on the uncached suffix — repeated
prefill becomes a KV copy, directly attacking TTFT.

Leases: a lookup that hits acquires the matched nodes' refcounts and returns a
`PrefixLease` the caller holds for the request's lifetime (eviction respects
in-flight slots — a popular system prompt cannot be churned out from under the
requests using it). The slot's seeded data is a COPY, so a lease is an
anti-churn pin, not a data dependency; `shrink` releases the tail of a lease
when the scheduler truncates a slot's reusable history (clamped parks,
runtime/batch_engine.py _park_positions).

Locking: one lock covers the tree and the pool together — lookups come from
the BatchEngine scheduler thread and (in single-slot mode) HTTP handler
threads concurrently.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field

import numpy as np

from ..obs import metrics
from .block_pool import KVBlockPool
from .radix import RadixIndex, RadixNode

__all__ = ["PrefixCache", "PrefixLease"]

# Cross-request prefix cache telemetry (docs/PREFIX_CACHE.md). Counters are
# process-global (all engines in the process share the family); per-instance
# copies live on PrefixCache for bench/stats isolation.
_HITS = metrics.counter(
    "prefix_cache_hits_total",
    "Lookups whose cached blocks were actually applied to a slot")
_MISSES = metrics.counter(
    "prefix_cache_misses_total", "Prompt lookups with no cached block")
_UNUSED = metrics.counter(
    "prefix_cache_unused_hits_total",
    "Lookups that matched blocks the slot rewind already covered (discarded)")
_HIT_TOKENS = metrics.counter(
    "prefix_cache_hit_tokens_total",
    "Prompt tokens served from cached KV blocks instead of prefill")
_RESIDENT_TOKENS = metrics.counter(
    "prefix_cache_resident_tokens_total",
    "Prompt tokens covered by the slot's own resident rewind (reuse that "
    "never touched the pool)")
_EVICTED = metrics.counter(
    "prefix_cache_evicted_blocks_total", "Blocks LRU-evicted from the pool")
_INSERTED = metrics.counter(
    "prefix_cache_inserted_blocks_total", "Blocks committed to the pool")
_POOL_BLOCKS = metrics.gauge(
    "prefix_cache_pool_blocks", "Blocks resident in the pool (hot + cold)")
_POOL_HOT = metrics.gauge(
    "prefix_cache_pool_hot_blocks", "Blocks resident in the uncompressed tier")
_POOL_BYTES = metrics.gauge(
    "prefix_cache_pool_bytes", "Host bytes held by the block pool")
_TREE_NODES = metrics.gauge(
    "prefix_cache_tree_nodes", "Nodes in the radix index")


@dataclass
class PrefixLease:
    """Refcount pin on the radix chain a request was seeded from. `tokens` is
    the seeded token count (may end mid-block: block data is copied into the
    slot, so partial use of the last block is free)."""

    nodes: list[RadixNode] = field(default_factory=list)
    tokens: int = 0


class PrefixCache:
    def __init__(self, max_blocks: int, block_tokens: int = 16,
                 hot_blocks: int | None = None, q80: bool = False):
        self.block_tokens = block_tokens
        self.radix = RadixIndex(block_tokens)
        self.pool = KVBlockPool(max_blocks, hot_blocks=hot_blocks, q80=q80)
        self._lock = threading.Lock()  # guards: radix, hits, misses, unused_hits, hit_tokens, resident_tokens, evicted_blocks, prompt_tokens
        # per-instance accounting (the module counters aggregate all instances).
        # hits/hit_tokens count APPLIED seeds (mark_seeded), not mere matches —
        # a match the slot rewind already covered served nothing from the pool
        # and must not inflate the reuse ratio (mark_unused counts it aside).
        self.hits = 0
        self.misses = 0
        self.unused_hits = 0
        self.hit_tokens = 0
        self.resident_tokens = 0
        self.evicted_blocks = 0
        self.prompt_tokens = 0  # all prompt tokens seen by lookup()

    # ------------------------------------------------------------------
    # lookup / release
    # ------------------------------------------------------------------

    def lookup(self, prompt: list[int], cap: int | None = None
               ) -> PrefixLease | None:
        """Longest cached block-prefix of `prompt`, as an acquired lease.

        The reuse length is capped at len(prompt) - 1 (the last prompt token
        must be re-inferred for logits, same rule as the reference NaiveCache)
        and at `cap` (callers pass seq_len - 1). Returns None on a miss; on a
        match the lease's nodes are acquired and MUST be handed back exactly
        once: mark_seeded (the caller applied the rows) + release at request
        end, or mark_unused (discarded). No block data is read here — callers
        decide whether the lease beats their own rewind first, then fetch():
        a discarded match must not pay the row gather."""
        with self._lock:
            self.prompt_tokens += len(prompt)
            nodes = self.radix.match(prompt)
            n = len(nodes) * self.block_tokens
            n = min(n, len(prompt) - 1)
            if cap is not None:
                n = min(n, cap)
            if n < 1:
                self.misses += 1
                _MISSES.inc()
                return None
            nodes = nodes[:(n + self.block_tokens - 1) // self.block_tokens]
            self.radix.acquire(nodes)
        return PrefixLease(nodes, n)

    def fetch(self, lease: PrefixLease, skip: int = 0
              ) -> tuple[np.ndarray, np.ndarray]:
        """Gather the lease's rows [skip, lease.tokens) as (K, V) host arrays
        of shape (L, hk, lease.tokens - skip, hs), ready to scatter into a
        slot's cache rows (`skip` = what the slot's own rewind already holds).
        Views into one fetch_packed buffer — callers that scatter both halves
        to device should use fetch_packed directly (one transfer)."""
        packed = self.fetch_packed(lease, skip)
        return packed[0], packed[1]

    def fetch_packed(self, lease: PrefixLease, skip: int = 0) -> np.ndarray:
        """Gather the lease's rows [skip, lease.tokens) as ONE contiguous
        host buffer of shape (2, L, hk, n, hs) ([0] = K, [1] = V), each block
        copied straight into place — no per-block concatenate + slice +
        re-contiguize round trip — so the seeding path pays a single
        host->device transfer and one scatter per cache tensor
        (batch_engine._seed_from_cache).

        Runs OUTSIDE the facade lock: a cold fetch dequantizes Q80 buffers,
        which must not stall concurrent lookups/inserts. The lease's refs pin
        the blocks (free() only happens via radix eviction, which respects
        refs), the caller owns the lease exclusively, and pool.get tolerates
        a concurrent demotion."""
        bt = self.block_tokens
        n = lease.tokens - skip
        first = skip // bt
        off = skip - first * bt
        out = None
        col = 0
        for node in lease.nodes[first:]:
            bk, bv = self.pool.get(node.handle)
            if out is None:
                L, hk, _, hs = bk.shape
                out = np.empty((2, L, hk, n, hs), bk.dtype)
            m = min(bk.shape[2] - off, n - col)
            out[0, :, :, col:col + m] = bk[:, :, off:off + m]
            out[1, :, :, col:col + m] = bv[:, :, off:off + m]
            col += m
            off = 0
            if col >= n:
                break
        return out

    def mark_seeded(self, lease: PrefixLease, used_tokens: int) -> None:
        """The caller scattered this lease's rows into a slot: count the hit.
        `used_tokens` is what the pool actually served — the seeded span
        beyond whatever the slot's own rewind already covered."""
        with self._lock:
            self.hits += 1
            self.hit_tokens += used_tokens
        _HITS.inc()
        _HIT_TOKENS.inc(used_tokens)

    def note_resident(self, tokens: int) -> None:
        """The engine's own slot rewind covered `tokens` leading prompt tokens
        before the pool was even consulted. Counted separately from hit_tokens
        (nothing was read from the pool) so reuse accounting doesn't depend on
        WHICH mechanism skipped the prefill — the fleet bench sums both
        (docs/FLEET.md): whether a sticky route lands on the slot that still
        holds the prefix (rewind) or a sibling slot (radix seed) is a
        scheduling accident, not a locality difference."""
        if tokens <= 0:
            return
        with self._lock:
            self.resident_tokens += tokens
        _RESIDENT_TOKENS.inc(tokens)

    def mark_unused(self, lease: PrefixLease | None) -> None:
        """The caller discarded the lease without applying it (the slot/
        resident rewind already covered the matched prefix, or the seed copy
        failed): releases it and counts it aside from the hit ratio."""
        if lease is None:
            return
        with self._lock:
            self.unused_hits += 1
        _UNUSED.inc()
        self.release(lease)

    def release(self, lease: PrefixLease | None) -> None:
        if lease is None:
            return
        with self._lock:
            # take-and-clear under the lock: two racing releasers (e.g.
            # BatchEngine.close() vs a scheduler thread alive past the join
            # timeout) must not double-decrement the refcounts
            nodes, lease.nodes = lease.nodes, []
            lease.tokens = 0
            if nodes:
                self.radix.release(nodes)

    def shrink(self, lease: PrefixLease, n_tokens: int) -> None:
        """Truncate a lease to `n_tokens`: blocks no part of [0, n_tokens)
        touches are released (the scheduler truncated the slot's reusable
        history — e.g. a clamped park overwrote its tail rows — so the pin
        on the now-irrelevant tail must not block eviction)."""
        if n_tokens >= lease.tokens:
            return
        keep = (max(n_tokens, 0) + self.block_tokens - 1) // self.block_tokens
        with self._lock:  # same take-and-clear discipline as release()
            drop, lease.nodes = lease.nodes[keep:], lease.nodes[:keep]
            lease.tokens = max(n_tokens, 0)
            if drop:
                self.radix.release(drop)

    # ------------------------------------------------------------------
    # insert
    # ------------------------------------------------------------------

    def insert(self, tokens: list[int], harvest) -> int:
        """Commit `tokens`' full blocks; returns how many NEW blocks landed.

        `harvest(t0, t1) -> (k, v)` supplies the (L, hk, t1-t0, hs) rows for
        token positions [t0, t1) — called at most ONCE, for the whole missing
        suffix (missing blocks are always a suffix: prefix-closed tree), so a
        device harvest pays one transfer however many blocks it fills. Tokens
        past the last full block are dropped (a partial block has no home)."""
        bt = self.block_tokens
        n_blocks = len(tokens) // bt
        blocked = tokens[:n_blocks * bt]
        if n_blocks == 0:
            return 0
        with self._lock:
            prefix_nodes = self.radix.match(blocked)
            have = len(prefix_nodes)
            if have >= n_blocks:
                return 0
            # pin the existing prefix: the harvest below runs OUTSIDE the lock
            # (it is a device->host transfer — holding the lock across it
            # would stall every concurrent lookup), and the batched eviction
            # further down must never take this chain's own ancestors
            self.radix.acquire(prefix_nodes)
        created = 0
        try:
            k_rows, v_rows = harvest(have * bt, n_blocks * bt)
            with self._lock:
                # a concurrent insert of the same prefix may have landed
                # blocks meanwhile; radix.insert skips them (harvest offsets
                # stay keyed to `have` — the pinned prefix cannot shrink)
                missing = n_blocks - len(self.radix.match(blocked))
                room = self.pool.max_blocks - len(self.pool)
                if room < missing:
                    # one batched eviction for the whole deficit instead of a
                    # full-tree sweep per block
                    freed = self.radix.evict(missing - room)
                    for h in freed:
                        self.pool.free(h)
                    self.evicted_blocks += len(freed)
                    _EVICTED.inc(len(freed))

                def make_handle(i: int) -> int | None:
                    nonlocal created
                    lo = (i - have) * bt
                    h = self.pool.put(k_rows[:, :, lo:lo + bt],
                                      v_rows[:, :, lo:lo + bt])
                    if h is not None:  # None: leases pinned the whole pool
                        created += 1
                    return h

                self.radix.insert(blocked, make_handle)
        finally:
            with self._lock:
                self.radix.release(prefix_nodes)
        _INSERTED.inc(created)
        self._publish_gauges()
        return created

    def covered_blocks(self, tokens: list[int]) -> int:
        """How many leading FULL blocks of `tokens` the index currently
        holds — import accounting (docs/DISAGG.md): the caller reports the
        span the cache can actually serve, not the span it was handed. No
        refs acquired; touches LRU stamps like any match."""
        with self._lock:
            return len(self.radix.match(tokens))

    def total_refs(self) -> int:
        """Live reservation count, read under the lock (a scheduler-thread
        insert may be mutating the tree concurrently)."""
        with self._lock:
            return self.radix.total_refs()

    # ------------------------------------------------------------------
    # stats
    # ------------------------------------------------------------------

    def _publish_gauges(self) -> None:
        # under the lock: hot_count/nbytes iterate the pool's block dict,
        # which a concurrent insert/evict mutates
        with self._lock:
            blocks, hot = len(self.pool), self.pool.hot_count()
            nbytes, nodes = self.pool.nbytes(), self.radix.nodes
        _POOL_BLOCKS.set(blocks)
        _POOL_HOT.set(hot)
        _POOL_BYTES.set(nbytes)
        _TREE_NODES.set(nodes)

    def stats(self) -> dict:
        """JSON-able snapshot (bench output, /v1/stats)."""
        with self._lock:
            looked = self.hits + self.unused_hits + self.misses
            return {
                "hits": self.hits, "misses": self.misses,
                "unused_hits": self.unused_hits,
                "hit_tokens": self.hit_tokens,
                "resident_tokens": self.resident_tokens,
                "prompt_tokens": self.prompt_tokens,
                "hit_rate": (self.hit_tokens / self.prompt_tokens
                             if self.prompt_tokens else 0.0),
                "reuse_rate": ((self.hit_tokens + self.resident_tokens)
                               / self.prompt_tokens
                               if self.prompt_tokens else 0.0),
                "lookup_hit_rate": ((self.hits + self.unused_hits) / looked
                                    if looked else 0.0),
                "evicted_blocks": self.evicted_blocks,
                "pool_blocks": len(self.pool),
                "pool_hot_blocks": self.pool.hot_count(),
                "pool_capacity_blocks": self.pool.max_blocks,
                "pool_bytes": self.pool.nbytes(),
                "demoted_blocks": self.pool.demoted_blocks,
                "tree_nodes": self.radix.nodes,
                "block_tokens": self.block_tokens,
                "q80_tier": self.pool.q80,
            }
