"""Shared KV-block wire codec: one serialize/deserialize story for KV rows.

Two consumers share this module:

- `cache/block_pool.py` — the host block pool's Q80 cold tier. Its
  hot→cold demotion and cold `get()` used to inline the quantize/dequantize
  round trip; `q80_compress`/`q80_restore` are that exact round trip,
  extracted so the in-RAM tier and the network wire can never drift apart
  (a block demoted here and a block decoded off the wire reconstruct
  through the SAME arithmetic).

- the disaggregation transfer layer (docs/DISAGG.md) — a prefill replica
  exports `(K, V)` block pairs over HTTP to a decode replica.
  `encode_blocks`/`decode_blocks` frame them: per-block header (mode,
  dtype, shape) + payload, with two modes per the EQuARX-style lesson that
  compressed collectives halve wire bytes at no serving-fidelity cost:

    * ``raw`` — the engine-dtype bytes verbatim. BIT-EXACT: a decode
      replica seeded from a raw wire block replays the prefill replica's
      rows exactly, so greedy/seeded generation is byte-identical to a
      local prefill.
    * ``q80`` — `quants.quantize_q80` over the flattened rows (34 bytes
      per 32 values, ~3.8x denser than f32). Bounded error, not bit-exact
      — the same capacity-over-exactness trade the cold tier documents
      (docs/PREFIX_CACHE.md). Blocks whose element count is not a multiple
      of the Q80 group size fall back to raw (never true for even head
      sizes); the mode byte is per block, so a mixed stream decodes fine.

The framing is self-describing (dtype name + shape per block): the decoder
needs no out-of-band schema, and a truncated buffer raises instead of
yielding garbage — a mid-transfer death surfaces as an exception the
import path's fallback-to-local-prefill catches (docs/DISAGG.md "Failure
semantics").
"""

from __future__ import annotations

import struct

import numpy as np

from ..quants import QK, dequantize_q80, quantize_q80

__all__ = ["q80_compress", "q80_restore", "q80_compressible",
           "encode_blocks", "decode_blocks", "block_wire_bytes"]

_MAGIC = b"DKW1"
_RAW, _Q80 = 0, 1
_HDR = struct.Struct("<4sBB")       # magic, mode, ndim  (+ dtype-name pascal)
_DIM = struct.Struct("<I")
_LEN = struct.Struct("<Q")


# ----------------------------------------------------------------------
# Q80 round trip (the block pool's cold tier, extracted)
# ----------------------------------------------------------------------

def q80_compressible(shape) -> bool:
    """Q80 quantizes flat groups of QK values; an array whose element count
    does not divide into them stays raw (block_pool keeps such blocks hot)."""
    return int(np.prod(shape)) % QK == 0


def q80_compress(arr: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """(values int8, scales f16) of the flattened array — the cold tier's
    storage pair and the wire's Q80 payload. f32 intermediary: quantize_q80
    upcasts anyway, and bf16 ndarrays (ml_dtypes) don't support every ufunc
    the quantizer uses."""
    n = int(np.prod(arr.shape))
    return quantize_q80(np.asarray(arr, np.float32).reshape(n))


def q80_restore(pair: tuple[np.ndarray, np.ndarray], shape,
                dtype) -> np.ndarray:
    """Dequantize a q80_compress pair back to (shape, dtype) — Q80
    round-trip precision, not bit-exact (see module docstring)."""
    return dequantize_q80(*pair).reshape(shape).astype(dtype)


# ----------------------------------------------------------------------
# wire framing
# ----------------------------------------------------------------------

def _dtype_from_name(name: str) -> np.dtype:
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes  # bf16 et al register through ml_dtypes

        return np.dtype(getattr(ml_dtypes, name))


def _encode_array(arr: np.ndarray, q80: bool) -> bytes:
    arr = np.ascontiguousarray(arr)
    mode = _Q80 if (q80 and q80_compressible(arr.shape)) else _RAW
    name = str(arr.dtype).encode("ascii")
    head = [_HDR.pack(_MAGIC, mode, arr.ndim),
            bytes([len(name)]), name]
    for d in arr.shape:
        head.append(_DIM.pack(d))
    if mode == _RAW:
        payload = arr.tobytes()
        return b"".join(head) + _LEN.pack(len(payload)) + payload
    vals, scales = q80_compress(arr)
    vb, sb = vals.tobytes(), np.ascontiguousarray(scales).tobytes()
    return (b"".join(head) + _LEN.pack(len(vb)) + vb
            + _LEN.pack(len(sb)) + sb)


def _decode_array(buf: memoryview, off: int) -> tuple[np.ndarray, int]:
    magic, mode, ndim = _HDR.unpack_from(buf, off)
    if magic != _MAGIC:
        raise ValueError(f"bad KV wire magic {magic!r} at offset {off}")
    off += _HDR.size
    nlen = buf[off]
    off += 1
    dtype = _dtype_from_name(bytes(buf[off:off + nlen]).decode("ascii"))
    off += nlen
    shape = []
    for _ in range(ndim):
        shape.append(_DIM.unpack_from(buf, off)[0])
        off += _DIM.size
    shape = tuple(shape)
    (n,) = _LEN.unpack_from(buf, off)
    off += _LEN.size
    if off + n > len(buf):
        raise ValueError("truncated KV wire payload")
    if mode == _RAW:
        arr = np.frombuffer(buf[off:off + n], dtype=dtype).reshape(shape)
        return arr.copy(), off + n
    vals = np.frombuffer(buf[off:off + n], np.int8)
    off += n
    (m,) = _LEN.unpack_from(buf, off)
    off += _LEN.size
    if off + m > len(buf):
        raise ValueError("truncated KV wire scales")
    scales = np.frombuffer(buf[off:off + m], np.float16)
    # re-group the flat wire payload into quantize_q80's (groups, QK) planar
    # layout so the restore runs the pool's exact dequant arithmetic
    if vals.size != scales.size * QK:
        raise ValueError("KV wire q80 values/scales size mismatch")
    return q80_restore((vals.reshape(-1, QK), scales), shape, dtype), off + m


def encode_blocks(blocks: list, q80: bool = False) -> bytes:
    """Frame a list of (K, V) block pairs — each side an (L, hk, bt, hs)
    host array — into one wire buffer. `q80` selects the compressed mode
    per array (incompressible shapes fall back to raw)."""
    out = [_LEN.pack(len(blocks))]
    for k, v in blocks:
        out.append(_encode_array(k, q80))
        out.append(_encode_array(v, q80))
    return b"".join(out)


def decode_blocks(data: bytes) -> list[tuple[np.ndarray, np.ndarray]]:
    """Inverse of encode_blocks; raises ValueError on any truncation or
    framing corruption (the import path treats that as a failed transfer)."""
    buf = memoryview(data)
    try:
        (count,) = _LEN.unpack_from(buf, 0)
        off = _LEN.size
        blocks = []
        for _ in range(count):
            k, off = _decode_array(buf, off)
            v, off = _decode_array(buf, off)
            blocks.append((k, v))
    except (struct.error, IndexError) as e:
        # struct under-runs on a cut buffer must surface as the one
        # documented failure type, not leak encoding internals
        raise ValueError(f"truncated/corrupt KV wire buffer: {e}") from None
    return blocks


def block_wire_bytes(blocks: list, q80: bool = False) -> int:
    """Exact encoded size without building the buffer (stats/planning)."""
    total = _LEN.size
    for k, v in blocks:
        for arr in (k, v):
            n = int(np.prod(arr.shape))
            name = len(str(arr.dtype))
            head = _HDR.size + 1 + name + _DIM.size * arr.ndim
            if q80 and q80_compressible(arr.shape):
                groups = n // QK
                total += head + 2 * _LEN.size + groups * QK + groups * 2
            else:
                total += head + _LEN.size + n * np.dtype(arr.dtype).itemsize
    return total
