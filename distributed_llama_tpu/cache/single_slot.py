"""Single-slot (Engine) client of the shared-prefix cache.

Supersedes api_server's NaiveCache: the same resident-conversation rewind the
reference implements (dllama-api.cpp:187-232) PLUS the cross-conversation
radix path — after conversation A is displaced by conversation B, a return to
A (or any prompt sharing A's system-prompt blocks) seeds the engine cache
from the pool instead of re-prefilling.

Two reuse sources, best wins:
- resident: the engine's live KV still holds the previous conversation;
  longest common token prefix rewinds `pos` (Engine.seek) — token-granular,
  zero copies, works in every engine mode including paged.
- radix: cached blocks cover a longer prefix than the resident KV does; the
  rows beyond the resident-common point are copied into the engine cache and
  `pos` moves FORWARD to the seeded length. Plain (non-paged) engines only:
  the paged ring's slot-position formula has no notion of rows that were
  never appended to the host store, so paged engines keep resident-only
  semantics (exactly the old NaiveCache).

The API server's generation lock serializes callers, so begin/end pairs never
interleave; the PrefixCache itself is still internally locked (it may be
shared with other clients).
"""

from __future__ import annotations

import numpy as np

from ..obs import trace
from .prefix_cache import PrefixCache, PrefixLease

__all__ = ["SingleSlotCache"]


class SingleSlotCache:
    def __init__(self, engine, cache: PrefixCache | None):
        self.engine = engine
        # paged mode: resident-only (see module docstring)
        self.cache = None if (cache is None or engine.paged) else cache
        self.resident: list[int] = []  # tokens whose KV the engine holds
        self._lease: PrefixLease | None = None

    def _resident_common(self, prompt: list[int]) -> int:
        n = 0
        for a, b in zip(self.resident, prompt):
            if a != b:
                break
            n += 1
        # never reuse the full prompt — the last token must be re-inferred
        return min(n, max(len(prompt) - 1, 0))

    def begin(self, prompt: list[int]) -> int:
        """Prepare the engine for `prompt`; returns how many leading tokens are
        already in its KV (the caller prefills only prompt[reuse:])."""
        eng = self.engine
        reuse = self._resident_common(prompt)
        if self.cache is not None:
            self.cache.note_resident(reuse)
            cap = eng.spec.seq_len - 1
            lease = self.cache.lookup(prompt, cap=cap)
            if lease is not None and lease.tokens > reuse:
                try:
                    with trace.span("api.prefix_seed",
                                    {"tokens": lease.tokens,
                                     "resident": reuse}):
                        eng.seek(min(reuse, eng.pos))
                        # fetch only beyond the resident rows; broadcast over
                        # the batch axis — the single-slot host loop tiles one
                        # sequence across every cache row
                        ck, cv = self.cache.fetch(lease, skip=reuse)
                        kk = np.asarray(ck[:, None], eng.k_cache.dtype)
                        vv = np.asarray(cv[:, None], eng.v_cache.dtype)
                        eng.k_cache = eng.k_cache.at[
                            :, :, :, reuse:lease.tokens, :].set(kk)
                        eng.v_cache = eng.v_cache.at[
                            :, :, :, reuse:lease.tokens, :].set(vv)
                        eng.pos = lease.tokens  # forward "seek": rows now exist
                except Exception as e:
                    # a partial write may have corrupted rows >= reuse of the
                    # RESIDENT conversation too — truncate the reuse record to
                    # the rows still known-good and fall back to plain prefill
                    # (the cache is an optimization, never a correctness gate)
                    self.cache.mark_unused(lease)
                    self.resident = self.resident[:reuse]
                    from . import warn_degraded

                    warn_degraded("seed", e)  # fall back to full prefill
                    eng.seek(min(reuse, eng.pos))
                    return reuse
                self._lease = lease
                self.cache.mark_seeded(lease, lease.tokens - reuse)
                self.resident = list(prompt[:lease.tokens])
                return lease.tokens
            self.cache.mark_unused(lease)
        eng.seek(min(reuse, eng.pos))
        return reuse

    def end(self, committed: list[int]) -> None:
        """Record the finished request's engine-resident tokens and harvest
        their full blocks into the pool. `committed` must be exactly the
        tokens whose KV is written — (prompt + out)[:engine.pos]."""
        eng = self.engine
        try:
            if self.cache is not None and committed:
                def harvest(t0: int, t1: int):
                    # row 0 of the (tiled) batch holds the sequence
                    return (np.asarray(eng.k_cache[:, 0, :, t0:t1]),
                            np.asarray(eng.v_cache[:, 0, :, t0:t1]))

                with trace.span("api.prefix_insert",
                                {"tokens": len(committed)}):
                    self.cache.insert(committed, harvest)
        except Exception as e:
            # the generation SUCCEEDED — a failed harvest must neither fail
            # the request nor leak the lease (an unreleased lease pins its
            # blocks unevictably forever)
            from . import warn_degraded

            warn_degraded("insert", e)
        finally:
            if self.cache is not None:
                self.cache.release(self._lease)
            self._lease = None
            self.resident = list(committed)

    def invalidate(self) -> None:
        """Generation failed mid-write: the engine KV is not trustworthy."""
        if self.cache is not None:
            self.cache.release(self._lease)
        self._lease = None
        self.resident = []

    def stats(self) -> dict | None:
        return self.cache.stats() if self.cache is not None else None
