"""Bounded host-side KV block store: F32/BF16 hot tier + optional Q80 cold tier.

Also home to `HostKVArena` — the RAM-or-disc (memmap) K/V arena that backs
every host-side KV spill in the repo: the long-context paged engine's
authoritative store (runtime/paged_cache.py HostKVStore delegates its
storage here) and, together with `KVBlockPool`, the device block pool's
cold tier (cache/device_pool.py demotes evicted directory blocks into a
KVBlockPool). One storage module, one cleanup discipline, one metric
family — the pre-ISSUE-12 state had paged_cache.py carrying its own
memmap + weakref-finalizer duplicate of this logic.

Each block holds the committed (K, V) rows of `block_tokens` consecutive
positions for every layer — shape (L, hk, block_tokens, hs) per side, exactly
the slice a slot's contiguous (B, hk, S, hs) device cache rows scatter from /
gather into (runtime/batch_engine.py admission seed and finish harvest).

Tiering applies the Opt4GPTQ co-optimization idea (PAPERS.md) to cache
capacity: hot blocks keep the engine dtype bit-exactly (a hot hit reproduces
the original prefill's rows and therefore the original tokens exactly); when
the hot tier overflows its budget, the LRU hot blocks are demoted to Q80
(quants.quantize_q80 over the flattened rows — 34 bytes per 32 values,
~3.8x denser than f32) and a cold hit pays one dequantize. Blocks whose
element count is not a multiple of the Q80 block size stay hot (never true
for even head sizes).

The pool never evicts on its own: cache/prefix_cache.py drives eviction
through the radix index (which knows refcounts and LRU order) and calls
`free` with the handles the tree surrenders. No internal lock for the same
reason — the facade's single lock covers tree + pool together.
"""

from __future__ import annotations

import itertools

import numpy as np

from .wire import q80_compress, q80_compressible, q80_restore

__all__ = ["HostKVArena", "KVBlockPool"]


class HostKVArena:
    """A (K, V) ndarray pair in host RAM ("host") or an np.memmap'd file
    pair ("disc"), with the self-cleaning temp-directory discipline the
    paged engine pioneered: a store whose directory WE created is removed
    at GC-or-exit via weakref.finalize (never atexit — that would pin every
    store for the process lifetime and leak multi-GB cache pairs across
    repeated in-process engine constructions); a caller-supplied directory
    is owner-kept. The one storage backend for every host-side KV spill
    (module docstring)."""

    def __init__(self, shape: tuple, dtype, *, storage: str = "host",
                 directory: str | None = None,
                 names: tuple[str, str] = ("key.cache", "value.cache")):
        import os

        assert storage in ("host", "disc"), storage
        self.storage = storage
        self.paths: tuple[str, str] | None = None
        self._owned_dir: str | None = None
        if storage == "disc":
            import shutil
            import tempfile
            import weakref

            if directory is None:
                directory = tempfile.mkdtemp(prefix="dlt_kv_cache_")
                self._owned_dir = directory
                self._finalizer = weakref.finalize(
                    self, shutil.rmtree, directory, ignore_errors=True)
            os.makedirs(directory, exist_ok=True)
            self.paths = (os.path.join(directory, names[0]),
                          os.path.join(directory, names[1]))
            self.k = np.memmap(self.paths[0], dtype=dtype, mode="w+",
                               shape=shape)
            self.v = np.memmap(self.paths[1], dtype=dtype, mode="w+",
                               shape=shape)
        else:
            self.k = np.zeros(shape, dtype)
            self.v = np.zeros(shape, dtype)

    def cleanup(self) -> None:
        """Delete the file pair + directory IF this arena created the
        directory itself. Idempotent; detaches the GC/exit finalizer."""
        if not self._owned_dir:
            return
        self._owned_dir = None
        self.k = self.v = None  # drop the memmaps before unlinking
        self._finalizer()

    def nbytes(self) -> int:
        return self.k.nbytes + self.v.nbytes


class _Block:
    __slots__ = ("k", "v", "kq", "vq", "shape", "dtype", "seq")

    def __init__(self, k: np.ndarray, v: np.ndarray, seq: int):
        self.k = k            # hot: ndarray (L, hk, N, hs); None when cold
        self.v = v
        self.kq = None        # cold: (values int8, scales f16) of the flat rows
        self.vq = None
        self.shape = k.shape
        self.dtype = k.dtype
        self.seq = seq        # hot-LRU clock value of the last touch

    @property
    def cold(self) -> bool:
        return self.k is None

    def nbytes(self) -> int:
        if self.cold:
            return sum(q[0].nbytes + q[1].nbytes for q in (self.kq, self.vq))
        return self.k.nbytes + self.v.nbytes


class KVBlockPool:
    def __init__(self, max_blocks: int, hot_blocks: int | None = None,
                 q80: bool = False):
        assert max_blocks >= 1
        self.max_blocks = max_blocks
        # q80 off => everything stays hot (the bit-exact default; the
        # acceptance bar is token-identical output with the cache enabled)
        self.hot_blocks = (max_blocks if not q80
                           else max(1, hot_blocks if hot_blocks is not None
                                    else max_blocks // 4))
        self.q80 = q80
        self._blocks: dict[int, _Block] = {}
        self._next_handle = 0
        # LRU clock. itertools.count: get() runs OUTSIDE the facade lock
        # (prefix_cache.fetch) concurrently with locked put/demote — a plain
        # `+= 1` there would lose increments and hand two blocks the same
        # stamp, steering the q80 demotion at the wrong "LRU" block
        self._seq = itertools.count(1)
        self.demoted_blocks = 0  # lifetime hot->Q80 demotions (stats)

    def __len__(self) -> int:
        return len(self._blocks)

    @property
    def full(self) -> bool:
        return len(self._blocks) >= self.max_blocks

    def hot_count(self) -> int:
        return sum(1 for b in self._blocks.values() if not b.cold)

    def nbytes(self) -> int:
        return sum(b.nbytes() for b in self._blocks.values())

    # ------------------------------------------------------------------

    def put(self, k: np.ndarray, v: np.ndarray) -> int | None:
        """Commit one block (copies taken); returns a handle, or None when the
        pool is at capacity (caller evicts via the radix index and retries)."""
        if self.full:
            return None
        assert k.shape == v.shape
        h = self._next_handle
        self._next_handle += 1
        self._blocks[h] = _Block(np.array(k, copy=True), np.array(v, copy=True),
                                 next(self._seq))
        self._maybe_demote()
        return h

    def get(self, handle: int) -> tuple[np.ndarray, np.ndarray]:
        """Block data in its original dtype/shape; a cold block dequantizes
        (Q80 round-trip precision, not bit-exact — see module docstring).

        Callers may read outside the facade lock (prefix_cache.lookup), so a
        concurrent demotion can clear b.k between a tier check and the read —
        snapshot the hot arrays once and fall through to the cold path when
        they vanished (demotion assigns kq/vq BEFORE clearing k/v)."""
        b = self._blocks[handle]
        b.seq = next(self._seq)
        k, v = b.k, b.v
        if k is not None and v is not None:  # demotion may land between reads
            return k, v
        k = q80_restore(b.kq, b.shape, b.dtype)
        v = q80_restore(b.vq, b.shape, b.dtype)
        return k, v

    def is_cold(self, handle: int) -> bool:
        return self._blocks[handle].cold

    def free(self, handle: int) -> None:
        del self._blocks[handle]

    # ------------------------------------------------------------------

    def _maybe_demote(self) -> None:
        if not self.q80:
            return
        import heapq

        hot = [b for b in self._blocks.values() if not b.cold]
        excess = len(hot) - self.hot_blocks
        if excess <= 0:
            return
        # nsmallest over the (normally 1-deep) excess: O(H), not a full sort
        # per put — a harvest inserts block-by-block and each put can push the
        # tier over budget by at most one
        compressible = (b for b in hot if q80_compressible(b.shape))
        for b in heapq.nsmallest(excess, compressible, key=lambda b: b.seq):
            # cache/wire.py owns the round trip (shared with the disagg
            # wire codec so the tiers can never drift apart)
            b.kq = q80_compress(b.k)
            b.vq = q80_compress(b.v)
            b.k = b.v = None
            self.demoted_blocks += 1
