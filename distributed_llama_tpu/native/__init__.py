"""ctypes bindings for the native host-runtime library (src/dlt_native.cpp).

Compiled on first use with the system toolchain (g++, no pip packages) and cached next
to the source; every entry point has a pure-Python/numpy fallback, so `available()`
returning False only means slower loads/encodes, never missing functionality. The split
mirrors the reference, where the host runtime (weight streaming transformer.cpp,
tokenizer.cpp) is C++ while we keep the accelerator math in XLA/Pallas.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import platform
import subprocess
import threading

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "src", "dlt_native.cpp")


def _host_tag() -> str:
    """ISA fingerprint for the build cache: the .so is compiled -march=native, so a
    checkout shared across heterogeneous hosts (NFS, reused container image) must not
    load another machine's binary — that SIGILLs at call time, past the build/dlopen
    try/except."""
    flags = ""
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.startswith(("flags", "Features")):
                    flags = line
                    break
    except OSError:
        pass
    h = hashlib.sha256(f"{platform.machine()}:{flags}".encode()).hexdigest()[:12]
    return f"{platform.machine()}-{h}"


_SO = os.path.join(_DIR, "_build", f"dlt_native-{_host_tag()}.so")

_lock = threading.Lock()
_lib: ctypes.CDLL | None | bool = None  # None = not tried, False = unavailable


def _build() -> str | None:
    try:
        os.makedirs(os.path.dirname(_SO), exist_ok=True)
        if os.path.exists(_SO) and os.path.getmtime(_SO) >= os.path.getmtime(_SRC):
            return _SO
        # per-process temp name: concurrent first-use builds must not race on one
        # .tmp path; os.replace promotion is atomic
        tmp = f"{_SO}.{os.getpid()}.tmp"
        cmd = ["g++", "-O3", "-march=native", "-shared", "-fPIC", "-std=c++17",
               "-pthread", _SRC, "-o", tmp]
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        os.replace(tmp, _SO)
        return _SO
    except Exception:
        return None


def _load() -> ctypes.CDLL | bool:
    so = _build()
    if so is None:
        return False
    try:
        lib = _bind(ctypes.CDLL(so))
    except (OSError, AttributeError):
        # AttributeError = stale cached .so missing a newer symbol (mtime check can
        # be fooled on NFS/image-layer checkouts): fall back, never crash
        return False
    return lib


def _bind(lib: ctypes.CDLL) -> ctypes.CDLL:
    i64, u8p, u16p, i8p, f32p, i32p = (
        ctypes.c_int64, ctypes.POINTER(ctypes.c_uint8),
        ctypes.POINTER(ctypes.c_uint16), ctypes.POINTER(ctypes.c_int8),
        ctypes.POINTER(ctypes.c_float), ctypes.POINTER(ctypes.c_int32))
    lib.dlt_q40_deinterleave.argtypes = [u8p, i64, u8p, u16p]
    lib.dlt_q80_deinterleave.argtypes = [u8p, i64, i8p, u16p]
    lib.dlt_q40_to_i8.argtypes = [u8p, u16p, i64, i8p, f32p]
    lib.dlt_q40_to_i4p.argtypes = [u8p, i64, i64, u8p]
    lib.dlt_f16_to_f32.argtypes = [u16p, i64, f32p]
    lib.dlt_xorshift_f32_fill.restype = ctypes.c_uint64
    lib.dlt_xorshift_f32_fill.argtypes = [ctypes.c_uint64, i64, ctypes.c_double, f32p]
    lib.dlt_bpe_create.restype = ctypes.c_void_p
    lib.dlt_bpe_create.argtypes = [u8p, ctypes.POINTER(i64), f32p, i64]
    lib.dlt_bpe_destroy.argtypes = [ctypes.c_void_p]
    lib.dlt_bpe_encode.restype = i64
    lib.dlt_bpe_encode.argtypes = [ctypes.c_void_p, u8p, i64, i32p]
    return lib


def _get() -> ctypes.CDLL | None:
    global _lib
    if _lib is None:
        with _lock:
            if _lib is None:
                _lib = _load()
    return _lib if _lib is not False else None


def available() -> bool:
    return _get() is not None


def _ptr(a: np.ndarray, ctype):
    return a.ctypes.data_as(ctypes.POINTER(ctype))


def q40_deinterleave(buf, nb: int) -> tuple[np.ndarray, np.ndarray] | None:
    """Interleaved Q40 block stream -> (qs (nb, 16) u8, deltas (nb,) f16)."""
    lib = _get()
    if lib is None:
        return None
    src = np.frombuffer(buf, dtype=np.uint8, count=nb * 18)
    qs = np.empty((nb, 16), np.uint8)
    d = np.empty((nb,), np.uint16)
    lib.dlt_q40_deinterleave(_ptr(src, ctypes.c_uint8), nb,
                             _ptr(qs, ctypes.c_uint8), _ptr(d, ctypes.c_uint16))
    return qs, d.view(np.float16)


def q80_deinterleave(buf, nb: int) -> tuple[np.ndarray, np.ndarray] | None:
    lib = _get()
    if lib is None:
        return None
    src = np.frombuffer(buf, dtype=np.uint8, count=nb * 34)
    qs = np.empty((nb, 32), np.int8)
    d = np.empty((nb,), np.uint16)
    lib.dlt_q80_deinterleave(_ptr(src, ctypes.c_uint8), nb,
                             _ptr(qs, ctypes.c_int8), _ptr(d, ctypes.c_uint16))
    return qs, d.view(np.float16)


def q40_to_i8(packed: np.ndarray, scales: np.ndarray
              ) -> tuple[np.ndarray, np.ndarray] | None:
    """Planar Q40 (..., nb, 16) u8 + (..., nb) f16 -> (int8 (..., nb*32), f32 scales)."""
    lib = _get()
    if lib is None:
        return None
    nb = int(np.prod(packed.shape[:-1], initial=1))
    p = np.ascontiguousarray(packed).reshape(nb, 16)
    d = np.ascontiguousarray(scales, dtype=np.float16).reshape(nb)
    vals = np.empty((nb, 32), np.int8)
    sc = np.empty((nb,), np.float32)
    lib.dlt_q40_to_i8(_ptr(p, ctypes.c_uint8), _ptr(d.view(np.uint16), ctypes.c_uint16),
                      nb, _ptr(vals, ctypes.c_int8), _ptr(sc, ctypes.c_float))
    lead = packed.shape[:-2]
    nbl = packed.shape[-2]
    return vals.reshape(*lead, nbl * 32), sc.reshape(*lead, nbl)


def q40_to_i4p(packed: np.ndarray, col_groups: int = 1) -> np.ndarray | None:
    """Planar Q40 (..., nb, 16) u8 -> split-plane packed nibbles (..., nb*16) u8,
    packed per column group (QTensor.to_i4p_layout's hot loop; scales pass through
    unchanged at the caller)."""
    lib = _get()
    if lib is None:
        return None
    lead = packed.shape[:-2]
    nbl = packed.shape[-2]
    if (nbl * 32) % col_groups or (nbl * 32 // col_groups) % 64:
        return None
    kl = nbl * 32 // col_groups
    units = int(np.prod(lead, initial=1)) * col_groups
    p = np.ascontiguousarray(packed).reshape(units, -1)
    out = np.empty((units, kl // 2), np.uint8)
    lib.dlt_q40_to_i4p(_ptr(p, ctypes.c_uint8), units, kl, _ptr(out, ctypes.c_uint8))
    return out.reshape(*lead, nbl * 16)


def xorshift_f32_fill(state: int, n: int, div: float = 1.0
                      ) -> tuple[np.ndarray, int] | None:
    """n draws of the reference's xorshift* randomF32 stream, each divided by `div`
    in double precision (bit-exact with `randomF32(&state) / div`). Returns
    (values f32 (n,), final state); None when the native library is unavailable
    (the stream is sequential — a Python fallback would be minutes for the
    200M-float golden-test weight streams, so callers skip instead)."""
    lib = _get()
    if lib is None:
        return None
    out = np.empty(n, np.float32)
    end = lib.dlt_xorshift_f32_fill(ctypes.c_uint64(state), n, div,
                                    _ptr(out, ctypes.c_float))
    return out, int(end)


class NativeBPE:
    """Native greedy-merge BPE encoder over a TokenizerData vocab."""

    def __init__(self, vocab: list[bytes], scores: list[float]):
        lib = _get()
        if lib is None:
            raise RuntimeError("native library unavailable")
        self._lib = lib
        blob = b"".join(vocab)
        offsets = np.zeros(len(vocab) + 1, np.int64)
        np.cumsum([len(v) for v in vocab], out=offsets[1:])
        self._blob = np.frombuffer(blob, np.uint8) if blob else np.zeros(1, np.uint8)
        self._scores = np.asarray(scores, np.float32)
        self._handle = lib.dlt_bpe_create(
            _ptr(self._blob, ctypes.c_uint8), _ptr(offsets, ctypes.c_int64),
            _ptr(self._scores, ctypes.c_float), len(vocab))

    def encode(self, raw: bytes) -> list[int] | None:
        """Token ids, or None when the vocab can't byte-fallback this input (the
        caller's Python path then reports the error)."""
        n = len(raw)
        src = np.frombuffer(raw, np.uint8) if n else np.zeros(1, np.uint8)
        out = np.empty(n + 1, np.int32)
        cnt = self._lib.dlt_bpe_encode(self._handle, _ptr(src, ctypes.c_uint8), n,
                                       _ptr(out, ctypes.c_int32))
        return out[:cnt].tolist() if cnt >= 0 else None

    def __del__(self):
        lib = getattr(self, "_lib", None)
        if lib is not None and getattr(self, "_handle", None):
            lib.dlt_bpe_destroy(self._handle)
