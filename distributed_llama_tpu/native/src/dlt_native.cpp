// Native host-runtime kernels for distributed_llama_tpu.
//
// The TPU owns the forward pass (XLA/Pallas), but the host runtime around it —
// model-file decode and tokenization — is the same kind of work the reference
// implements in C++ (src/transformer.cpp weight streaming, src/tokenizer.cpp BPE).
// These are fresh implementations of this framework's own host formats, built as a
// shared library loaded via ctypes (see native/__init__.py; every entry point has a
// pure-numpy/Python fallback, so the library is an accelerator, not a dependency).
//
// Contents:
//   - f16 -> f32 scalar conversion (scale decode)
//   - Q40/Q80 interleaved block streams -> planar arrays (the .m tensor layout,
//     reference struct layout quants.hpp:17-25)
//   - Q40 planar -> int8 planes (the Pallas q8 kernel's on-device layout,
//     ops/pallas_q8.py)
//   - llama2.c-style BPE encoder (greedy highest-score pair merging, byte fallback;
//     behavior-parity with tokenizer/bpe.py which itself mirrors src/tokenizer.cpp)
//
// All bulk transforms are threaded over block ranges with std::thread.

#include <atomic>
#include <cstdint>
#include <cstring>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>

namespace {

constexpr int QK = 32;

float f16_to_f32(uint16_t h) {
    uint32_t sign = (uint32_t)(h & 0x8000u) << 16;
    uint32_t exp = (h >> 10) & 0x1F;
    uint32_t mant = h & 0x3FF;
    uint32_t bits;
    if (exp == 0) {
        if (mant == 0) {
            bits = sign;  // +-0
        } else {  // subnormal: normalize
            int shift = 0;
            while (!(mant & 0x400)) { mant <<= 1; ++shift; }
            mant &= 0x3FF;
            bits = sign | ((127 - 15 - shift + 1) << 23) | (mant << 13);
        }
    } else if (exp == 31) {
        bits = sign | 0x7F800000u | (mant << 13);  // inf/nan
    } else {
        bits = sign | ((exp - 15 + 127) << 23) | (mant << 13);
    }
    float out;
    std::memcpy(&out, &bits, sizeof(out));
    return out;
}

template <typename F>
void parallel_blocks(int64_t n, F body, int64_t item_bytes = 16) {
    unsigned hw = std::thread::hardware_concurrency();
    int64_t nthreads = (int64_t)(hw ? hw : 4);
    // don't spawn threads for < ~64 KB of work each
    int64_t max_useful = (n * item_bytes) / 65536;
    if (nthreads > max_useful) nthreads = max_useful;
    if (nthreads <= 1) { body((int64_t)0, n); return; }
    std::vector<std::thread> ts;
    int64_t per = (n + nthreads - 1) / nthreads;
    for (int64_t t = 0; t < nthreads; ++t) {
        int64_t lo = t * per, hi = lo + per < n ? lo + per : n;
        if (lo >= hi) break;
        ts.emplace_back([=] { body(lo, hi); });
    }
    for (auto& t : ts) t.join();
}

}  // namespace

extern "C" {

// Q40 interleaved stream (18 B/block: f16 delta + 16 nibble-pair bytes) ->
// planar qs (nb, 16) u8 + deltas (nb,) f16 (raw u16 bits, converted later or not).
void dlt_q40_deinterleave(const uint8_t* blocks, int64_t nb, uint8_t* qs_out,
                          uint16_t* d_out) {
    parallel_blocks(nb, [=](int64_t lo, int64_t hi) {
        for (int64_t i = lo; i < hi; ++i) {
            const uint8_t* b = blocks + i * 18;
            std::memcpy(d_out + i, b, 2);
            std::memcpy(qs_out + i * 16, b + 2, 16);
        }
    });
}

// Q80 interleaved stream (34 B/block: f16 delta + 32 int8) -> planar.
void dlt_q80_deinterleave(const uint8_t* blocks, int64_t nb, int8_t* qs_out,
                          uint16_t* d_out) {
    parallel_blocks(nb, [=](int64_t lo, int64_t hi) {
        for (int64_t i = lo; i < hi; ++i) {
            const uint8_t* b = blocks + i * 34;
            std::memcpy(d_out + i, b, 2);
            std::memcpy(qs_out + i * QK, b + 2, QK);
        }
    });
}

// Planar Q40 (nb, 16) u8 + f16 deltas -> int8 planes (nb*32,) natural order
// (block b: cols [b*32, b*32+16) = low nibbles - 8, [b*32+16, b*32+32) = high - 8)
// + f32 scales. This is QTensor.to_i8_layout's hot loop.
void dlt_q40_to_i8(const uint8_t* packed, const uint16_t* d16, int64_t nb,
                   int8_t* vals_out, float* scales_out) {
    parallel_blocks(nb, [=](int64_t lo, int64_t hi) {
        for (int64_t i = lo; i < hi; ++i) {
            const uint8_t* q = packed + i * 16;
            int8_t* v = vals_out + i * QK;
            for (int j = 0; j < 16; ++j) {
                v[j] = (int8_t)(q[j] & 0x0F) - 8;
                v[j + 16] = (int8_t)(q[j] >> 4) - 8;
            }
            scales_out[i] = f16_to_f32(d16[i]);
        }
    });
}

// Planar Q40 -> split-plane packed nibbles ("i4p", QTensor.to_i4p_layout's hot loop):
// per (row, column-group) unit of kl elements, output byte j = q[j] | (q[j+kl/2] << 4)
// where q is the natural-order stored nibble (already carries the +8 offset). Scales
// pass through untouched (they stay f16). `units` = rows * col_groups.
void dlt_q40_to_i4p(const uint8_t* packed, int64_t units, int64_t kl, uint8_t* out) {
    const int64_t nbg = kl / QK, kh = kl / 2;
    parallel_blocks(units, [=](int64_t lo, int64_t hi) {
        for (int64_t u = lo; u < hi; ++u) {
            const uint8_t* src = packed + u * nbg * 16;
            uint8_t* dst = out + u * kh;
            auto nib = [&](int64_t e) -> uint8_t {
                int64_t b = e >> 5, p = e & 31;  // block, position within block
                uint8_t byte = src[b * 16 + (p & 15)];
                return p < 16 ? (uint8_t)(byte & 0x0F) : (uint8_t)(byte >> 4);
            };
            for (int64_t j = 0; j < kh; ++j)
                dst[j] = (uint8_t)(nib(j) | (nib(j + kh) << 4));
        }
    }, kh);
}

// f16 bits -> f32 array (Q80 scale decode and general .m f16 tensors).
void dlt_f16_to_f32(const uint16_t* in, int64_t n, float* out) {
    parallel_blocks(n, [=](int64_t lo, int64_t hi) {
        for (int64_t i = lo; i < hi; ++i) out[i] = f16_to_f32(in[i]);
    });
}

// xorshift* f32 stream, bit-exact with the reference's randomU32/randomF32
// (src/utils.cpp:79-90) including the double-precision divide its golden tests
// apply to each draw (e.g. `randomF32(&state) / 120.0`, llama2-tasks-test.cpp:561).
// Sequential by construction (each draw feeds the next state), hence native.
// Returns the final state so callers can continue the stream.
uint64_t dlt_xorshift_f32_fill(uint64_t state, int64_t n, double div, float* out) {
    for (int64_t i = 0; i < n; ++i) {
        state ^= state >> 12;
        state ^= state << 25;
        state ^= state >> 27;
        uint32_t u = (uint32_t)((state * 0x2545F4914F6CDD1Dull) >> 32);
        float f = (float)(u >> 8) / 16777216.0f;  // randomF32: <0,1)
        out[i] = (float)((double)f / div);
    }
    return state;
}

// ---------------------------------------------------------------------------
// BPE encoder (behavior-parity with tokenizer/bpe.py <- src/tokenizer.cpp:170-292)
// ---------------------------------------------------------------------------

struct DltBpe {
    std::vector<std::string> vocab;
    std::vector<float> scores;
    std::unordered_map<std::string, int32_t> lookup;  // first occurrence wins
    int32_t space_id = -1;
};

void* dlt_bpe_create(const uint8_t* blob, const int64_t* offsets,
                     const float* scores, int64_t n) {
    auto* h = new DltBpe();
    h->vocab.reserve(n);
    h->scores.assign(scores, scores + n);
    h->lookup.reserve((size_t)n * 2);
    for (int64_t i = 0; i < n; ++i) {
        std::string piece((const char*)(blob + offsets[i]),
                          (size_t)(offsets[i + 1] - offsets[i]));
        h->vocab.push_back(piece);
        h->lookup.emplace(std::move(piece), (int32_t)i);  // keeps first duplicate
    }
    auto it = h->lookup.find(" ");
    if (it != h->lookup.end()) h->space_id = it->second;
    return h;
}

void dlt_bpe_destroy(void* hp) { delete (DltBpe*)hp; }

// Encode raw bytes (no BOS/EOS — the Python wrapper owns those) into out;
// returns the token count. out must hold >= text_len + 1 entries.
int64_t dlt_bpe_encode(void* hp, const uint8_t* text, int64_t text_len,
                       int32_t* out) {
    auto* h = (DltBpe*)hp;
    std::vector<int32_t> toks;
    toks.reserve((size_t)text_len + 1);
    if (text_len > 0 && h->space_id >= 0) toks.push_back(h->space_id);  // dummy prefix

    // UTF-8 codepoint chunking with byte fallback (+3 offset). A fallback id past the
    // vocab (non-llama2.c vocab layout) would read out of bounds in the merge loop
    // below — return -1 and let the Python wrapper take its (cleanly raising) path.
    const int32_t n_vocab = (int32_t)h->vocab.size();
    int64_t i = 0;
    std::string chunk;
    while (i < text_len) {
        int64_t j = i + 1;
        while (j < text_len && (text[j] & 0xC0) == 0x80 && (j - i) < 4) ++j;
        chunk.assign((const char*)(text + i), (size_t)(j - i));
        auto it = h->lookup.find(chunk);
        if (it != h->lookup.end()) {
            toks.push_back(it->second);
        } else {
            for (int64_t b = i; b < j; ++b) {
                int32_t id = (int32_t)text[b] + 3;
                if (id >= n_vocab) return -1;
                toks.push_back(id);
            }
        }
        i = j;
    }

    // greedy highest-score adjacent pair merging
    std::string merged;
    while (true) {
        float best_score = -1e10f;
        int32_t best_id = -1;
        int64_t best_idx = -1;
        for (int64_t k = 0; k + 1 < (int64_t)toks.size(); ++k) {
            merged = h->vocab[(size_t)toks[k]];
            merged += h->vocab[(size_t)toks[k + 1]];
            auto it = h->lookup.find(merged);
            if (it != h->lookup.end() && h->scores[(size_t)it->second] > best_score) {
                best_score = h->scores[(size_t)it->second];
                best_id = it->second;
                best_idx = k;
            }
        }
        if (best_idx < 0) break;
        toks[(size_t)best_idx] = best_id;
        toks.erase(toks.begin() + best_idx + 1);
    }

    std::memcpy(out, toks.data(), toks.size() * sizeof(int32_t));
    return (int64_t)toks.size();
}

}  // extern "C"
