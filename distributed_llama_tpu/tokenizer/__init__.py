from .bpe import Tokenizer  # noqa: F401
from .chat import ChatItem, ChatTemplate, TemplateType  # noqa: F401
from .eos import EosDetector, EosResult  # noqa: F401
