"""Chat template engine — the reference's four hardcoded templates with substring
auto-detection of the tokenizer's embedded Jinja template (src/tokenizer.cpp:436-500)."""

from __future__ import annotations

import enum
from dataclasses import dataclass


class TemplateType(enum.Enum):
    UNKNOWN = "unknown"
    LLAMA2 = "llama2"
    LLAMA3 = "llama3"
    ZEPHYR = "zephyr"
    CHATML = "chatml"


@dataclass
class ChatItem:
    role: str
    message: str


class ChatTemplate:
    def __init__(self, ttype: TemplateType | str, chat_template: str | None,
                 eos: str):
        if isinstance(ttype, str):
            ttype = TemplateType(ttype)
        if ttype == TemplateType.UNKNOWN:
            if chat_template is None:
                raise ValueError("the tokenizer does not include a chat template")
            if "[INST]" in chat_template:
                ttype = TemplateType.LLAMA2
            elif "<|start_header_id|>" in chat_template:
                ttype = TemplateType.LLAMA3
            elif "<|user|>" in chat_template:
                ttype = TemplateType.ZEPHYR
            elif "<|im_start|>" in chat_template:
                ttype = TemplateType.CHATML
            else:
                raise ValueError("unsupported chat template")
        self.type = ttype
        self.eos = eos

    def generate(self, items: list[ChatItem], append_generation_prompt: bool = True) -> str:
        """Reference ChatTemplate::generate (tokenizer.cpp:468-500), verbatim behavior."""
        eos = self.eos
        out: list[str] = []
        if self.type == TemplateType.LLAMA2:
            i = 0
            if len(items) >= 2 and items[0].role == "system" and items[1].role == "user":
                out.append(f"[INST] <<SYS>>\n{items[0].message}\n<</SYS>>\n\n"
                           f"{items[1].message} [/INST]{eos}")
                i = 2
            for item in items[i:]:
                if item.role == "assistant":
                    out.append(f"{item.message}{eos}")
                elif item.role == "user":
                    out.append(f"[INST] {item.message} [/INST]{eos}")
        elif self.type == TemplateType.LLAMA3:
            for item in items:
                out.append(f"<|start_header_id|>{item.role}<|end_header_id|>\n\n"
                           f"{item.message}{eos}")
            if append_generation_prompt:
                out.append("<|start_header_id|>assistant<|end_header_id|>\n\n")
        elif self.type == TemplateType.CHATML:
            for item in items:
                out.append(f"<|im_start|>{item.role}\n{item.message}<|im_end|>\n")
            if append_generation_prompt:
                out.append("<|im_start|>assistant\n")
        elif self.type == TemplateType.ZEPHYR:
            for item in items:
                out.append(f"<|{item.role}|>\n{item.message}{eos}\n")
            if append_generation_prompt:
                out.append("<|assistant|>\n")
        return "".join(out)
