"""Streaming stop-sequence detector.

Behavior-parity port of EosDetector (src/tokenizer.cpp:502-575): matches stop strings
that may be split across token boundaries, tolerating `padding_left` junk bytes before
and `padding_right` after the stop string inside the held-back window, and short-circuits
on the EOS token id. Operates on bytes (token pieces may be partial UTF-8).
"""

from __future__ import annotations

import enum


class EosResult(enum.Enum):
    NOT_EOS = 0
    MAYBE_EOS = 1
    EOS = 2


class EosDetector:
    def __init__(self, eos_ids: int | list[int], stops: list[bytes | str],
                 padding_left: int = 0, padding_right: int = 0):
        self.eos_ids = {eos_ids} if isinstance(eos_ids, int) else set(eos_ids)
        self.stops = [s.encode() if isinstance(s, str) else s for s in stops]
        self.padding_left = padding_left
        self.padding_right = padding_right
        self.buffer = bytearray()
        self.eos_pos = -1

    def append(self, token_id: int, piece: bytes) -> EosResult:
        piece_start = len(self.buffer)
        self.buffer += piece

        if token_id in self.eos_ids:
            self.eos_pos = piece_start
            return EosResult.EOS
        self.eos_pos = -1

        n_buf = len(self.buffer)
        for stop in self.stops:
            stop_size = len(stop)
            if n_buf > stop_size + self.padding_left + self.padding_right:
                continue
            for lo in range(self.padding_left + 1):
                n = n_buf - lo
                if n == 0 or n > stop_size + self.padding_right:
                    continue
                n = min(n, stop_size)
                if self.buffer[lo:lo + n] == stop[:n]:
                    if n == stop_size:
                        self.eos_pos = lo
                        return EosResult.EOS
                    return EosResult.MAYBE_EOS
        return EosResult.NOT_EOS

    def get_delta(self) -> bytes | None:
        """Printable bytes accumulated so far (up to the stop match, if any)."""
        if self.eos_pos == -1:
            return bytes(self.buffer) or None
        if self.eos_pos == 0:
            return None
        return bytes(self.buffer[:self.eos_pos])

    def clear(self) -> None:
        self.buffer.clear()


class TokenStreamer:
    """Drives an EosDetector over a token stream, emitting printable deltas.

    Shared state machine for CLI chat and the API server: holds back bytes that might be
    the start of a stop sequence, flushes them when they turn out not to be, and reports
    when generation should stop."""

    def __init__(self, detector: EosDetector, decode_piece, emit):
        self.detector = detector
        self.decode_piece = decode_piece
        self.emit = emit
        self.stopped = False

    def on_token(self, token_id: int) -> None:
        res = self.detector.append(token_id, self.decode_piece(token_id))
        if res == EosResult.MAYBE_EOS:
            return  # hold back until resolved
        delta = self.detector.get_delta()
        if delta:
            self.emit(delta)
        if res == EosResult.EOS:
            self.stopped = True
        else:
            self.detector.clear()

    def stop_check(self, _token_id: int) -> bool:
        return self.stopped
