"""llama2.c-style BPE tokenizer (host-side, byte-oriented).

Behavior-parity port of the reference encoder/decoder (src/tokenizer.cpp:150-292):
greedy highest-score pair merging, UTF-8 codepoint pre-chunking, byte-fallback with the
+3 offset, dummy-prefix space, and the BOS leading-space decode strip. One deliberate
fix: the reference's byte-token detection compares `sscanf(...) == bosId`
(tokenizer.cpp:157) — a literal `== 1` only by accident of llama2's bosId; we treat a
successful `<0xXX>` parse as a byte token regardless of bosId.

Works on bytes throughout (vocab entries are raw byte strings from the `.t` file).
"""

from __future__ import annotations

import re

from ..formats.tfile import TokenizerData

_BYTE_TOKEN_RE = re.compile(rb"^<0x([0-9A-Fa-f]{2})>$")


class Tokenizer:
    def __init__(self, data: TokenizerData):
        self.data = data
        self.vocab = data.vocab
        self.scores = data.scores
        self.bos_id = data.bos_id
        self.eos_id = data.eos_id
        self.chat_eos_id = data.chat_eos_id if data.chat_eos_id >= 0 else data.eos_id
        self.chat_template = data.chat_template
        self.chat_stop = data.chat_stop
        # first occurrence wins for duplicate pieces (reference bsearch picks
        # an arbitrary duplicate; dict-of-first is deterministic)
        self._lookup: dict[bytes, int] = {}
        for i, piece in enumerate(self.vocab):
            self._lookup.setdefault(piece, i)
        self._byte_pieces: list[bytes | None] = [None] * len(self.vocab)
        for i, piece in enumerate(self.vocab):
            m = _BYTE_TOKEN_RE.match(piece)
            if m:
                self._byte_pieces[i] = bytes([int(m.group(1), 16)])
        self._native = None
        self._native_tried = False

    @classmethod
    def load(cls, path: str) -> "Tokenizer":
        from ..formats.tfile import load_tokenizer

        return cls(load_tokenizer(path))

    @property
    def vocab_size(self) -> int:
        return len(self.vocab)

    def eos_piece(self) -> str:
        """Printable chat-EOS string (used as the template's eos marker)."""
        if self.chat_eos_id >= 0:
            return self.vocab[self.chat_eos_id].decode("utf-8", "replace")
        return "</s>"

    def chat_stops(self) -> list[bytes]:
        """Stop byte-strings for chat generation (reference TokenizerChatStops,
        tokenizer.cpp:417-434): the chat-EOS piece plus the optional extra stop."""
        stops: list[bytes] = []
        if self.chat_eos_id >= 0:
            stops.append(self.vocab[self.chat_eos_id])
        if self.chat_stop:
            stops.append(self.chat_stop.encode())
        return stops

    def _native_bpe(self):
        """Lazily build the C++ encoder (native/); None if the library is unavailable."""
        if not self._native_tried:
            self._native_tried = True
            try:
                from .. import native

                if native.available():
                    self._native = native.NativeBPE(self.vocab, self.scores)
            except Exception:
                self._native = None
        return self._native

    def encode(self, text: str | bytes, add_bos: bool = False,
               add_eos: bool = False) -> list[int]:
        """Reference Tokenizer::encode (tokenizer.cpp:170-292)."""
        raw = text.encode("utf-8") if isinstance(text, str) else text
        tokens: list[int] = []
        if add_bos and self.bos_id >= 0:
            tokens.append(self.bos_id)
        nat = self._native_bpe()
        if nat is not None:
            ids = nat.encode(raw)
            if ids is not None:
                tokens.extend(ids)
                if add_eos and self.eos_id >= 0:
                    tokens.append(self.eos_id)
                return tokens
        if raw:
            dummy = self._lookup.get(b" ")
            if dummy is not None:
                tokens.append(dummy)

        # UTF-8 codepoint chunking: accumulate continuation bytes (max 4), then lookup
        i, n = 0, len(raw)
        while i < n:
            j = i + 1
            while j < n and (raw[j] & 0xC0) == 0x80 and (j - i) < 4:
                j += 1
            chunk = raw[i:j]
            tid = self._lookup.get(chunk)
            if tid is not None:
                tokens.append(tid)
            else:
                # byte fallback: first 3 vocab slots are <unk>, <s>, </s>
                tokens.extend(b + 3 for b in chunk)
            i = j

        # greedy merge: repeatedly merge the adjacent pair whose concatenation is the
        # highest-scoring vocab entry
        while True:
            best_score = -1e10
            best_id = -1
            best_idx = -1
            for k in range(len(tokens) - 1):
                merged = self.vocab[tokens[k]] + self.vocab[tokens[k + 1]]
                mid = self._lookup.get(merged)
                if mid is not None and self.scores[mid] > best_score:
                    best_score = self.scores[mid]
                    best_id = mid
                    best_idx = k
            if best_idx == -1:
                break
            tokens[best_idx:best_idx + 2] = [best_id]

        if add_eos and self.eos_id >= 0:
            tokens.append(self.eos_id)
        return tokens

    def decode_piece(self, prev_token: int, token: int) -> bytes:
        """Reference Tokenizer::decode (tokenizer.cpp:150-161): returns the raw bytes for
        one token given its predecessor (BOS leading-space strip)."""
        piece = self.vocab[token]
        if prev_token == self.bos_id and piece.startswith(b" "):
            piece = piece[1:]
        b = self._byte_pieces[token]
        if b is not None:
            return b
        return piece

    def decode(self, tokens: list[int]) -> str:
        out = bytearray()
        prev = self.bos_id if tokens and tokens[0] == self.bos_id else -1
        for t in tokens:
            if t == self.bos_id:
                prev = t
                continue
            out += self.decode_piece(prev, t)
            prev = t
        return out.decode("utf-8", errors="replace")
