"""ModelDrafter: a small sharded draft model co-resident on the target mesh
(docs/SERVING.md "Model-based drafting").

PR 8 built the general batched draft-verify machinery but fed it only n-gram
lookups, which go dry off repetition-heavy traffic. This drafter closes the
deferred hook: a second, much smaller model — loaded through the SAME
formats/converter path as the target (mfile loaders, Q40/Q80 supported) —
shares the target's mesh and drafts k tokens per row in ONE `lax.scan`
dispatch (draft/loop.py). The drafter's matmuls are tiny and memory-bound,
so co-residency steals negligible HBM bandwidth from the target model while
opening speculation to chat/code/open-ended rows.

Frontier bookkeeping (all host-side, scheduler thread only): per row the
drafter tracks `toks` (the row's full delivered stream: prompt ⊕ output —
re-attached whole on preemption re-admission and durable resume, so those
paths need nothing special), `frontier` (tokens whose KV the drafter has
ingested and CONFIRMED), and `spec_tail` (its own drafted tokens whose KV it
wrote speculatively during the last scan). When the target delivers a token
(push) that matches the head of spec_tail — exactly the accepted drafts, by
the verify identity — the frontier advances for FREE: the KV written while
drafting IS that token's KV. The first mismatch (the correction token)
drops the rest of the tail; its KV sits beyond the frontier on masked slots
and the next catch-up overwrites it — the same free-rollback discipline the
target engine uses. A proposal turn then force-ingests the handful of
pending tokens (usually just the correction/bonus) and free-runs k greedy
argmax steps, all in one bucketed scan dispatch for every served row.

Failure semantics: load and propose failures degrade — the caller
(runtime/speculative.py ProposerMux) falls back to n-gram drafting and
ultimately plain decode; a drafter can slow speculation down but never
surface to a client (fault points draft.load / draft.propose,
docs/ROBUSTNESS.md).
"""

from __future__ import annotations

import time

import numpy as np

from ..models.spec import ModelSpec
from ..obs import metrics, trace
from ..resilience import faults
from .loop import make_draft_loop, make_draft_step

_DISPATCHES = metrics.counter(
    "batch_draft_dispatches_total",
    "Drafter scan dispatches (one per served proposal turn)")
_DRAFTED = metrics.counter(
    "batch_draft_drafted_tokens_total",
    "Tokens drafted by the model drafter")
_CATCHUP = metrics.counter(
    "batch_draft_catchup_tokens_total",
    "Target-delivered tokens the drafter re-ingested in-scan to sync")
_PREFILL = metrics.counter(
    "batch_draft_prefill_tokens_total",
    "Tokens chunk-prefilled into the drafter KV (attach / long catch-up)")
_SPEC_HITS = metrics.counter(
    "batch_draft_frontier_hits_total",
    "Delivered tokens whose drafter KV was already written while drafting "
    "(frontier advanced with zero re-ingest work)")
_DISPATCH_SECONDS = metrics.histogram(
    "batch_draft_dispatch_seconds",
    "Wall time of one drafter scan dispatch")

# drafter prefill chunk: the drafter context is small and its weights tiny,
# so one shape covers attach-time catch-up without the target's 64-chunk —
# the sub-chunk tail is NOT prefilled token-by-token, it simply rides the
# proposal scan's catch-up phase (which runs anyway and carries up to
# catchup_cap tokens)
PREFILL_CHUNK = 16


class _Row:
    __slots__ = ("toks", "frontier", "spec_tail")

    def __init__(self, tokens: list[int]):
        self.toks = list(tokens)  # full stream: prompt ⊕ delivered output
        self.frontier = 0  # toks[:frontier] have confirmed drafter KV
        self.spec_tail: list[int] = []  # drafted tokens with speculative KV


class ModelDrafter:
    """Proposer-protocol drafter (runtime/speculative.py) backed by a small
    sharded model on the target's mesh. Scheduler-thread-only except
    stats(), which reads plain counters (a torn read only skews a stats
    scrape)."""

    name = "model"

    def __init__(self, spec: ModelSpec, params, *, mesh, slots: int,
                 target_spec: ModelSpec, tokenizer=None, dtype=None,
                 use_pallas: bool | str = False,
                 compress_collectives: bool = False,
                 moe_sharding: str = "slice", k_cap: int = 8):
        import jax.numpy as jnp

        from ..models.params import prepare_for_pallas
        from ..parallel.mesh import AXIS_TP
        from ..parallel.sharding import check_divisibility
        from ..parallel.tp import init_sharded_kv_cache, shard_params
        from ..ops.rope import RopeTables
        from ..quants import FloatType

        faults.fire("draft.load")
        # vocab compatibility: drafts are token IDS fed straight into the
        # target's verify block — the two models (and the serving tokenizer)
        # must share one vocabulary or every draft is garbage-at-best
        if spec.vocab_size != target_spec.vocab_size:
            raise ValueError(
                f"draft model vocab {spec.vocab_size} != target vocab "
                f"{target_spec.vocab_size} (the models must share a "
                "tokenizer)")
        if tokenizer is not None and tokenizer.vocab_size != spec.vocab_size:
            raise ValueError(
                f"draft model vocab {spec.vocab_size} != tokenizer vocab "
                f"{tokenizer.vocab_size}")
        tp = mesh.shape[AXIS_TP]
        check_divisibility(spec, tp, 1, moe_sharding=moe_sharding)
        self.spec = spec
        self.mesh = mesh
        self.slots = slots
        self.k_cap = max(int(k_cap), 1)
        # in-scan catch-up bound: past this the row chunk-prefills first.
        # 2k+1 covers the steady states (full-accept turn: 2 pending; a
        # K-step scan burst between verifies: K+1 pending)
        self.catchup_cap = 2 * self.k_cap + 1
        self.dtype = dtype if dtype is not None else jnp.float32
        # the POLICY passes through unchanged ("fused"/"all" string-valued):
        # the drafter's k-step scan is the ideal fusion victim — a small
        # model whose entire weight stream is the per-step cost
        has_quant = any(
            getattr(t, "ftype", None) in (FloatType.Q40, FloatType.Q80)
            for t in params["blocks"].values())
        self.use_pallas = use_pallas if has_quant else False
        self.compress = compress_collectives
        self.moe_sharding = moe_sharding if spec.is_moe else "slice"
        if self.use_pallas:
            params = prepare_for_pallas(
                params, tp, moe_sharding=self.moe_sharding, spec=spec,
                keep_gate_pair=self.use_pallas == "fused")
        self.params = shard_params(params, mesh, spec,
                                   moe_sharding=self.moe_sharding)
        self.rope = RopeTables.create(spec)
        self.k_cache, self.v_cache = init_sharded_kv_cache(
            spec, mesh, batch=slots, dtype=self.dtype)
        self._rows: dict[int, _Row] = {}
        self._loops: dict[int, object] = {}  # scan-length bucket -> program
        self._step = None  # chunked prefill forward
        self.dispatches = 0
        self.prefill_tokens = 0

    @classmethod
    def load(cls, path: str, **kw) -> "ModelDrafter":
        """Load a drafter from a `.m` model file — the exact loader the
        target uses (formats/mfile.py: Q40/Q80/F32, header schema, seq-len
        clamp)."""
        from ..formats.mfile import load_model

        spec, params = load_model(str(path))
        return cls(spec, params, **kw)

    # -- Proposer protocol ------------------------------------------------

    def attach(self, row: int, tokens: list[int]) -> None:
        self._rows[row] = _Row(tokens)

    def detach(self, row: int) -> None:
        self._rows.pop(row, None)

    def push(self, row: int, tok: int) -> None:
        st = self._rows.get(row)
        if st is None:
            return
        st.toks.append(tok)
        if st.spec_tail and st.spec_tail[0] == tok:
            # the target accepted this draft: the KV the drafter wrote
            # while drafting IS this token's KV — frontier advances free
            st.spec_tail.pop(0)
            st.frontier += 1
            _SPEC_HITS.inc()
        elif st.spec_tail:
            # correction/divergence: the rest of the tail's KV sits beyond
            # the frontier on masked slots (overwritten by the next scan)
            st.spec_tail.clear()

    def observe(self, row: int, accepted: int) -> None:
        pass  # frontier sync rides push(); accept EMAs live in AdaptiveK

    def can_serve(self, row: int, k: int) -> bool:
        """Room check: drafting k tokens needs the catch-up + k-1 fed-back
        drafts to fit the drafter's OWN context (which may be shorter than
        the target's — such rows fall back to n-gram drafting), and the
        stream to sit within one scan of the frontier cap."""
        st = self._rows.get(row)
        if st is None or k <= 0:
            return False
        pending = len(st.toks) - st.frontier
        return (pending >= 1 and len(st.toks) + k <= self.spec.seq_len
                and len(st.toks) <= self._frontier_cap() + self.catchup_cap)

    def stats(self) -> dict:
        return {"model": (f"dim{self.spec.dim}_L{self.spec.n_layers}"
                          f"_voc{self.spec.vocab_size}"
                          f"_s{self.spec.seq_len}"),
                "rows": len(self._rows), "k_cap": self.k_cap,
                "dispatches": self.dispatches,
                "prefill_tokens": self.prefill_tokens}

    # -- programs ---------------------------------------------------------

    def _loop(self, steps: int):
        if steps not in self._loops:
            self._loops[steps] = make_draft_loop(
                self.spec, self.mesh, self.params, steps, dtype=self.dtype,
                use_pallas=self.use_pallas,
                compress_collectives=self.compress, donate_cache=True,
                moe_sharding=self.moe_sharding)
        return self._loops[steps]

    def _prefill_step(self):
        if self._step is None:
            self._step = make_draft_step(
                self.spec, self.mesh, self.params, dtype=self.dtype,
                use_pallas=self.use_pallas,
                compress_collectives=self.compress, donate_cache=True,
                attn_window=None, cache_write="deferred",
                moe_sharding=self.moe_sharding)
        return self._step

    def reset_backend(self) -> None:
        """Wedge-recovery hook (BatchEngine.recover_wedged): drop compiled
        programs and re-allocate the KV caches — a zombie dispatch may still
        hold (and have donated) the old buffers — and force every row back
        to a clean re-prefill."""
        from ..parallel.tp import init_sharded_kv_cache

        self._loops.clear()
        self._step = None
        self.k_cache, self.v_cache = init_sharded_kv_cache(
            self.spec, self.mesh, batch=self.slots, dtype=self.dtype)
        self._rows.clear()

    # -- drafting ---------------------------------------------------------

    def _scan_bucket(self, need: int) -> int:
        from ..runtime.speculative import verify_block_bucket

        return verify_block_bucket(max(need, 2),
                                   self.catchup_cap + self.k_cap - 1)

    def _frontier_cap(self) -> int:
        """Global frontier ceiling G: every confirmed frontier is kept at or
        below G by the retreat pass at the top of propose_batch, sized so NO
        later dispatch's park clamp (scan width <= the bucket cap, prefill
        chunk <= PREFILL_CHUNK) can ever need to move a frontier again —
        a mid-loop retreat would silently invalidate another row's already-
        captured catch-up state (review-caught). Rows whose stream outgrows
        G + catchup_cap become unservable and fall back to n-gram drafting:
        near the drafter's own context wall its useful life is over anyway."""
        steps_cap = self.catchup_cap + self.k_cap - 1
        return max(self.spec.seq_len - max(steps_cap, PREFILL_CHUNK), 0)

    def _prefill_row(self, row: int, st: _Row) -> None:
        """Chunk-ingest pending tokens until the remainder fits one
        proposal scan (<= catchup_cap) — never token-by-token: the scan's
        catch-up phase runs anyway and carries the remainder for free, and
        a short final chunk runs PADDED through the same (B, 16) program
        (the pad's garbage KV lands beyond the advanced frontier on masked
        slots — the standard free-rollback discipline — so one compiled
        shape covers every prefill). Other rows ride the dispatches parked
        at their own frontiers — all <= the cap by the propose_batch
        retreat pass, so no scratch write can touch committed rows and no
        frontier moves here."""
        step = self._prefill_step()
        import jax.numpy as jnp

        # stop once the remaining pending rides one scan; never past the cap
        target = min(max(len(st.toks) - self.catchup_cap, st.frontier),
                     self._frontier_cap())
        t0 = time.perf_counter()
        n0 = st.frontier
        with trace.span("draft.prefill",
                        {"row": row, "tokens": target - n0}):
            while st.frontier < target:
                real = min(PREFILL_CHUNK, target - st.frontier)
                toks = np.zeros((self.slots, PREFILL_CHUNK), np.int32)
                starts = np.zeros((self.slots,), np.int32)
                for i, other in self._rows.items():
                    starts[i] = other.frontier
                toks[row, :real] = st.toks[st.frontier:st.frontier + real]
                starts[row] = st.frontier
                _, self.k_cache, self.v_cache = step(
                    self.params, self.rope, jnp.asarray(toks), self.k_cache,
                    self.v_cache, jnp.asarray(starts))
                st.frontier += real
                st.spec_tail.clear()
        n = st.frontier - n0
        self.prefill_tokens += n
        _PREFILL.inc(n)
        self._dt_note(t0)

    def _dt_note(self, t0: float) -> None:
        _DISPATCH_SECONDS.observe(time.perf_counter() - t0)

    def propose_batch(self, want: dict[int, int]) -> dict[int, list[int]]:
        """Draft up to want[row] tokens for every servable row in ONE scan
        dispatch. Rows the drafter cannot serve (no pending token, context
        exhausted) are absent from the result — the mux falls back to
        n-gram for them."""
        faults.fire("draft.propose", rows=len(want))
        s = self.spec.seq_len
        # retreat pass FIRST: pin every frontier at/below the global cap
        # before ANY row's catch-up state is captured, so neither the
        # prefill parks nor the scan parks below can move a frontier
        # mid-turn (the prefix below the cap stays valid; the retreated
        # tail re-ingests as ordinary catch-up)
        cap = self._frontier_cap()
        for other in self._rows.values():
            if other.frontier > cap:
                other.frontier = cap
                other.spec_tail.clear()
        serve: dict[int, tuple[_Row, int, int]] = {}  # row -> (st, ncatch, k)
        for row, k in want.items():
            st = self._rows.get(row)
            k = min(k, self.k_cap)
            if st is None or k <= 0:
                continue
            ncatch = len(st.toks) - st.frontier
            if ncatch <= 0:
                continue  # nothing pending (e.g. a retried plan): skip
            # context room: ncatch + k - 1 ingestions from `frontier` must
            # stay inside the drafter's seq_len
            k = min(k, s - st.frontier - ncatch)
            if k <= 0:
                continue
            if ncatch > self.catchup_cap:
                self._prefill_row(row, st)
                ncatch = len(st.toks) - st.frontier
                if ncatch <= 0 or ncatch > self.catchup_cap:
                    # a stream past cap+catchup_cap cannot be carried by
                    # one scan: the row falls back to n-gram drafting
                    continue
            st.spec_tail.clear()  # the scan overwrites the old tail's slots
            serve[row] = (st, ncatch, k)
        if not serve:
            return {}
        steps = self._scan_bucket(max(nc + k - 1 for _st, nc, k
                                      in serve.values()))
        catchup = np.zeros((self.slots, steps), np.int32)
        starts = np.zeros((self.slots,), np.int32)
        ncatch = np.zeros((self.slots,), np.int32)
        budget = np.zeros((self.slots,), np.int32)
        for i, other in self._rows.items():
            # parked rows ride with scratch writes at their own frontiers —
            # all at/below the cap, so every write is masked and in-bounds
            starts[i] = other.frontier
        for row, (st, nc, k) in serve.items():
            span = st.toks[st.frontier:st.frontier + min(nc, steps)]
            catchup[row, :len(span)] = span
            starts[row] = st.frontier
            ncatch[row] = nc
            budget[row] = nc + k - 1
        t0 = time.perf_counter()
        with trace.span("draft.propose",
                        {"rows": len(serve), "steps": steps,
                         "catchup": int(ncatch.sum())}):
            loop = self._loop(steps)
            toks, _pos, self.k_cache, self.v_cache = loop(
                self.params, self.rope, catchup, self.k_cache, self.v_cache,
                starts, ncatch, budget)
            # the drafter's one delivery fence: host-side proposal slicing
            # requires the (S, B) argmax block
            toks = np.asarray(toks)
        self.dispatches += 1
        _DISPATCHES.inc()
        self._dt_note(t0)
        out: dict[int, list[int]] = {}
        for row, (st, nc, k) in serve.items():
            drafts = toks[nc - 1:nc - 1 + k, row].tolist()
            st.frontier += nc
            # all but the last draft were fed back: their KV is written
            # speculatively at the positions the tokens would occupy
            st.spec_tail = drafts[:-1]
            out[row] = drafts
            _CATCHUP.inc(nc)
            _DRAFTED.inc(len(drafts))
        return out
