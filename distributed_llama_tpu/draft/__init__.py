"""Model-based speculative drafting (docs/SERVING.md "Model-based drafting").

A second, small sharded model co-resident on the target engine's mesh drafts
k tokens per row in one `lax.scan` dispatch; the target's existing batched
verify path (runtime/device_loop.py make_batched_verify_loop) then accepts or
rejects the drafts with the usual byte-identity guarantees. Lazily importing
(PEP 562) like the cache/fleet packages: importing the package costs nothing
until a drafter is actually constructed.
"""

_EXPORTS = {
    "ModelDrafter": ".drafter",
    "make_draft_loop": ".loop",
    "make_draft_step": ".loop",
}


def __getattr__(name):
    if name in _EXPORTS:
        import importlib

        mod = importlib.import_module(_EXPORTS[name], __name__)
        return getattr(mod, name)
    raise AttributeError(name)


__all__ = list(_EXPORTS)
