"""Drafter device programs: the catch-up + greedy-draft scan and the
chunked prefill step (docs/SERVING.md "Model-based drafting").

The drafter is a SECOND model sharded over the target engine's mesh, so its
programs mirror the target's idioms (runtime/device_loop.py) at the
drafter's own ModelSpec. Two programs live here:

- make_draft_loop: ONE `lax.scan` per proposal turn. Each row first
  force-ingests its catch-up tokens (target-delivered tokens the drafter has
  not yet seen — typically the correction/bonus token of the previous verify
  turn), then free-runs greedy argmax for k steps, feeding each draft back
  as the next input. Both phases share the scan body: step j of row r takes
  catchup[r, j] while j < ncatch[r], its own previous argmax afterwards, and
  parks (clamped scratch write, masked reads) past budget[r] = ncatch[r] +
  k[r] - 1. The host slices row r's drafts from the returned (S, B) argmax
  block at [ncatch[r]-1, ncatch[r]-1+k[r]). Scan lengths are bucketed
  (speculative.verify_block_bucket) so compile count stays O(log k).

- make_draft_step: the plain (B, T) forward for chunked catch-up prefill
  when a row's pending history exceeds what a scan should carry (fresh
  attach with a long prompt). A thin factory around
  parallel.tp.make_sharded_forward under its own name so the compile
  manifest (analysis/compile_audit.py) tracks drafter programs apart from
  the target's.

Drafting is greedy-only by design: drafts are PROPOSALS — the target's
verify samples with the request's real temperature/topp and the usual
acceptance identity holds for any proposal content, so the drafter never
needs the xorshift* machinery.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..models.forward import forward
from ..models.spec import ModelSpec
from ..ops.rope import RopeTables
from ..parallel.mesh import AXIS_SP
from ..parallel.sharding import kv_cache_pspec_for_mesh, param_pspecs
from ..parallel.tp import _expand_pspec_tree
from ..resilience import faults


def make_draft_step(spec: ModelSpec, mesh, params, **kw):
    """Chunked drafter forward — fn(params, rope, tokens (B, T), kc, vc,
    start_pos (B,)) -> (logits, kc, vc). Same contract as
    make_sharded_forward; a separate factory name so drafter prefill
    programs get their own compile-manifest key."""
    from ..parallel.tp import make_sharded_forward

    return make_sharded_forward(spec, mesh, params, **kw)


def make_draft_loop(spec: ModelSpec, mesh, params, steps: int, *,
                    dtype=None, use_pallas: bool = False,
                    compress_collectives: bool = False,
                    donate_cache: bool = True,
                    moe_sharding: str = "slice"):
    """Build the drafter's catch-up + draft scan.

    fn(params, rope, catchup (B, S), kc, vc, start_pos (B,), ncatch (B,),
    budget (B,)) -> (toks (S, B), pos (B,), kc, vc).

    Per row r: steps j < ncatch[r] force-ingest catchup[r, j] at position
    start_pos[r] + j; steps ncatch[r] <= j < budget[r] ingest the previous
    argmax (free-running draft). toks[j, r] is the argmax after step j's
    ingestion, so row r's k drafts are toks[ncatch[r]-1 : ncatch[r]-1+k, r].
    Rows with budget 0 park: their scratch writes land clamped inside the
    cache on masked slots (the free-rollback discipline — the row's next
    real catch-up overwrites them). KV advances budget[r] positions for
    live rows; drafted-token KV beyond the confirmed frontier is adopted by
    the drafter exactly when the target later delivers the same token
    (draft/drafter.py push).
    """
    from ..parallel.mesh import AXIS_DP

    dtype = dtype or jnp.float32
    assert steps >= 1
    assert mesh.shape.get(AXIS_SP, 1) == 1 and \
        mesh.shape.get(AXIS_DP, 1) == 1, "the drafter is tp-only"
    param_specs = _expand_pspec_tree(params, param_pspecs(params, moe_sharding))
    kv_spec = kv_cache_pspec_for_mesh(mesh)
    rope_type = spec.rope_type
    seq_len = spec.seq_len

    from ..runtime.device_loop import _tp_axis

    fwd = functools.partial(forward, spec=spec, dtype=dtype,
                            axis_name=_tp_axis(mesh, compress_collectives),
                            sp_axis_name=None, sp_size=1,
                            use_pallas=use_pallas,
                            compress_collectives=compress_collectives,
                            attn_window=None, cache_write="deferred")

    # hot-path: traced
    def loop(p, rope_cos, rope_sin, catchup, kc, vc, start_pos, ncatch,
             budget):
        rope = RopeTables(rope_cos, rope_sin, rope_type)

        def step(carry, j):
            tok, pos, kc, vc = carry
            live = j < budget  # (B,)
            forced = jax.lax.dynamic_index_in_dim(
                catchup, jnp.minimum(j, catchup.shape[1] - 1), axis=1,
                keepdims=False)  # (B,)
            inp = jnp.where(j < ncatch, forced, tok)
            step_pos = jnp.where(live, pos, jnp.minimum(pos, seq_len - 1))
            logits, kc, vc = fwd(p, rope=rope, tokens=inp[:, None],
                                 k_cache=kc, v_cache=vc, start_pos=step_pos)
            nxt = jnp.argmax(logits[:, -1].astype(jnp.float32),
                             axis=-1).astype(jnp.int32)
            tok = jnp.where(live, nxt, tok)
            pos = jnp.where(live, pos + 1, pos)
            return (tok, pos, kc, vc), nxt

        tok0 = catchup[:, 0]
        (tok, pos, kc, vc), toks = jax.lax.scan(
            step, (tok0, start_pos, kc, vc),
            jnp.arange(steps, dtype=jnp.int32))
        return toks, pos, kc, vc

    from ..compat import shard_map

    sharded = shard_map(
        loop, mesh=mesh,
        in_specs=(param_specs, P(), P(), P(), kv_spec, kv_spec, P(), P(),
                  P()),
        out_specs=(P(), P(), kv_spec, kv_spec),
        check_vma=False,
    )
    donate = (4, 5) if donate_cache else ()
    jitted = jax.jit(sharded, donate_argnums=donate)

    # hot-path
    def run(p, rope: RopeTables, catchup, kc, vc, start_pos, ncatch, budget):
        faults.fire("draft.dispatch", steps=steps)
        return jitted(p, rope.cos, rope.sin,
                      jnp.asarray(catchup, jnp.int32), kc, vc,
                      jnp.asarray(start_pos, jnp.int32),
                      jnp.asarray(ncatch, jnp.int32),
                      jnp.asarray(budget, jnp.int32))

    return run
