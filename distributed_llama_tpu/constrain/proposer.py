"""GrammarProposer — the constraint automaton as a draft source.

Implements the Proposer protocol (runtime/speculative.py): wherever the
row's automaton state sits on a forced-transition chain (singleton-mask
states — JSON punctuation, schema keys, closing brackets), the chain IS
the target model's only legal continuation, so proposing it gives
guaranteed accept without running any draft model. ProposerMux consults
it first for constrained rows; chat rows co-batched in the same engine
never reach it and keep their model/ngram drafts.

The proposer reads the engine's live per-slot constraint state (the same
object _emit advances), so propose() needs no corpus of its own — push()
and observe() are no-ops.
"""

from __future__ import annotations


class GrammarProposer:
    name = "grammar"

    def __init__(self) -> None:
        # row -> slot-constraint handle with .automaton / .state / .degraded
        self._rows: dict[int, object] = {}

    def attach_constraint(self, row: int, sc) -> None:
        self._rows[row] = sc

    def attach(self, row: int, tokens: list[int]) -> None:
        pass  # binding happens via attach_constraint at admission

    def detach(self, row: int) -> None:
        self._rows.pop(row, None)

    def push(self, row: int, tok: int) -> None:
        pass  # the engine advances the shared constraint state in _emit

    def observe(self, row: int, accepted: int) -> None:
        pass

    def propose(self, row: int, k: int) -> list[int]:
        sc = self._rows.get(row)
        if sc is None or sc.degraded or k <= 0:
            return []
        return sc.automaton.forced_chain(sc.state, k)

    def propose_batch(self, want: dict[int, int]) -> dict[int, list[int]]:
        return {row: d for row, k in want.items()
                if (d := self.propose(row, k))}

    def ready(self, row: int, k: int, min_draft: int) -> bool:
        return len(self.propose(row, k)) >= min_draft

    def stats(self) -> dict:
        return {"rows": len(self._rows)}
