"""Grammar forms -> TokenAutomaton, LRU-cached by grammar hash.

Three forms lower to the shared byte-regex core (automaton.py):

  json_schema  canonical-JSON regex (no inter-token whitespace; object
               properties emitted in declaration order, all required;
               enum/const/anyOf as alternation; $ref/allOf rejected)
  regex        the byte-regex subset directly
  grammar      non-recursive EBNF (`name ::= body`), rules inlined in
               dependency order; recursion is a CompileError

Repeated schemas compile once: the cache is keyed by
sha256(kind ⊕ source ⊕ vocab signature) — the same hash the api edge logs
into the flight-recorder timeline and /v1/stats reports per compile.
"""

from __future__ import annotations

import hashlib
import json
import re
import threading
from collections import OrderedDict

from ..obs import metrics
from ..resilience import faults
from .automaton import CompileError, TokenAutomaton, regex_token_automaton

_COMPILES = metrics.counter(
    "constrain_compile_total",
    "Grammar compiles by outcome (hit = LRU cache hit)",
    labelnames=("outcome",))

_CACHE_CAP = 64
_cache: OrderedDict[str, TokenAutomaton] = OrderedDict()
_lock = threading.Lock()  # guards: _cache, _stats
_stats = {"hits": 0, "misses": 0, "errors": 0}


def grammar_hash(kind: str, source) -> str:
    src = source if isinstance(source, str) else json.dumps(
        source, sort_keys=True, separators=(",", ":"))
    return hashlib.sha256(f"{kind}\x00{src}".encode()).hexdigest()[:16]


def compile_stats() -> dict:
    with _lock:
        return dict(_stats, size=len(_cache))


def _vocab_sig(vocab: list[bytes], eos_id: int) -> str:
    h = hashlib.sha256(str((len(vocab), eos_id)).encode())
    for p in vocab[:256]:
        h.update(p or b"\x00")
    return h.hexdigest()[:12]


def vocab_bytes(tokenizer) -> list[bytes]:
    """Per-token byte pieces as served: `<0xNN>` byte-fallback tokens decode
    to their raw byte, everything else to its vocab piece."""
    out = []
    for i, piece in enumerate(tokenizer.vocab):
        b = tokenizer._byte_pieces[i]
        out.append(b if b is not None else piece)
    return out


def byte_vocab(vocab_size: int, specials: tuple[int, ...] = (0, 1, 2)
               ) -> list[bytes]:
    """Synthetic vocab for tokenizer-less engines (tests, tiny benches):
    token i spells the single byte i % 256; special ids (unk/bos/eos) spell
    nothing and are therefore never grammar-allowed except EOS's dedicated
    accepting-state handling."""
    return [b"" if i in specials else bytes([i % 256])
            for i in range(vocab_size)]


def compile_grammar(kind: str, source, vocab: list[bytes], eos_id: int
                    ) -> tuple[TokenAutomaton, str]:
    """Compile (or fetch) the automaton for one grammar. Raises
    CompileError for malformed/unsupported grammars — the api edge maps it
    to an honest 400 before any queue work."""
    faults.fire("constrain.compile", kind=kind)
    ghash = grammar_hash(kind, source)
    key = f"{ghash}:{_vocab_sig(vocab, eos_id)}"
    with _lock:
        aut = _cache.get(key)
        if aut is not None:
            _cache.move_to_end(key)
            _stats["hits"] += 1
            _COMPILES.labels(outcome="hit").inc()
            return aut, ghash
    try:
        if kind == "json_schema":
            pattern = schema_to_regex(source)
        elif kind == "regex":
            if not isinstance(source, str):
                raise CompileError("regex source must be a string")
            pattern = source
        elif kind == "grammar":
            if not isinstance(source, str):
                raise CompileError("grammar source must be a string")
            pattern = ebnf_to_regex(source)
        else:
            raise CompileError(f"unknown grammar kind {kind!r}")
        aut = regex_token_automaton(pattern, vocab, eos_id,
                                    source_hash=ghash)
    except CompileError:
        with _lock:
            _stats["errors"] += 1
        _COMPILES.labels(outcome="error").inc()
        raise
    except RecursionError:
        with _lock:
            _stats["errors"] += 1
        _COMPILES.labels(outcome="error").inc()
        raise CompileError("grammar too deeply nested") from None
    with _lock:
        _cache[key] = aut
        _stats["misses"] += 1
        while len(_cache) > _CACHE_CAP:
            _cache.popitem(last=False)
    _COMPILES.labels(outcome="miss").inc()
    return aut, ghash


# ----------------------------------------------------------------------
# JSON Schema -> regex
# ----------------------------------------------------------------------

_ESC = {c: "\\" + c for c in "\\^$.|?*+()[]{}"}


def _rx_escape(s: str) -> str:
    return "".join(_ESC.get(c, c) for c in s)


_RX_STRING = '"(?:[^"\\\\\\x00-\\x1f]|\\\\["\\\\/bfnrt])*"'
_RX_INT = "-?(?:0|[1-9][0-9]{0,17})"
_RX_NUMBER = _RX_INT + "(?:\\.[0-9]{1,17})?(?:[eE][+-]?[0-9]{1,3})?"

_MAX_SCHEMA_DEPTH = 12


def schema_to_regex(schema) -> str:
    if isinstance(schema, str):
        try:
            schema = json.loads(schema)
        except ValueError as e:
            raise CompileError(f"json_schema is not valid JSON: {e}") from None
    if not isinstance(schema, dict):
        raise CompileError("json_schema must be an object")
    return _schema_rx(schema, 0)


def _schema_rx(schema: dict, depth: int) -> str:
    if depth > _MAX_SCHEMA_DEPTH:
        raise CompileError("json_schema nests too deep")
    if not isinstance(schema, dict):
        raise CompileError("schema node must be an object")
    for bad in ("$ref", "allOf", "not", "patternProperties"):
        if bad in schema:
            raise CompileError(f"unsupported json_schema keyword {bad!r}")
    if "const" in schema:
        return _rx_escape(json.dumps(schema["const"],
                                     separators=(",", ":")))
    if "enum" in schema:
        opts = schema["enum"]
        if not isinstance(opts, list) or not opts:
            raise CompileError("enum must be a non-empty array")
        return "(?:" + "|".join(
            _rx_escape(json.dumps(v, separators=(",", ":")))
            for v in opts) + ")"
    for alt_kw in ("anyOf", "oneOf"):
        if alt_kw in schema:
            alts = schema[alt_kw]
            if not isinstance(alts, list) or not alts:
                raise CompileError(f"{alt_kw} must be a non-empty array")
            return "(?:" + "|".join(_schema_rx(a, depth + 1)
                                    for a in alts) + ")"
    t = schema.get("type")
    if isinstance(t, list):
        return "(?:" + "|".join(
            _schema_rx(dict(schema, type=one), depth + 1) for one in t) + ")"
    if t == "string":
        if "pattern" in schema:
            # anchored pattern over the string BODY; the subset has no
            # anchors so the author's pattern constrains the full body
            return '"' + str(schema["pattern"]) + '"'
        lo = schema.get("minLength")
        hi = schema.get("maxLength")
        if lo is not None or hi is not None:
            lo = int(lo or 0)
            hi = int(hi if hi is not None else lo + 64)
            if hi < lo:
                raise CompileError("maxLength < minLength")
            ch = '(?:[^"\\\\\\x00-\\x1f]|\\\\["\\\\/bfnrt])'
            return f'"{ch}{{{lo},{hi}}}"'
        return _RX_STRING
    if t == "integer":
        return _RX_INT
    if t == "number":
        return _RX_NUMBER
    if t == "boolean":
        return "(?:true|false)"
    if t == "null":
        return "null"
    if t == "array":
        item = _schema_rx(schema.get("items", {"type": "string"}), depth + 1)
        lo = int(schema.get("minItems", 0))
        hi = schema.get("maxItems")
        if hi is None:
            if lo == 0:
                return f"\\[(?:{item}(?:,{item})*)?\\]"
            return f"\\[{item}(?:,{item}){{{lo - 1},}}\\]"
        hi = int(hi)
        if hi < lo:
            raise CompileError("maxItems < minItems")
        if hi == 0:
            return "\\[\\]"
        body = f"{item}(?:,{item}){{{max(lo - 1, 0)},{hi - 1}}}"
        return f"\\[(?:{body})?\\]" if lo == 0 else f"\\[{body}\\]"
    if t == "object":
        props = schema.get("properties", {})
        if not isinstance(props, dict):
            raise CompileError("properties must be an object")
        if not props:
            return "\\{\\}"
        # canonical emission: every declared property, declaration order,
        # no whitespace — the schema's one unambiguous serialization, so
        # forced-transition chains stay long (docs/SERVING.md)
        parts = [f"{_rx_escape(json.dumps(k))}:{_schema_rx(v, depth + 1)}"
                 for k, v in props.items()]
        return "\\{" + ",".join(parts) + "\\}"
    raise CompileError(f"unsupported json_schema type {t!r}")


# ----------------------------------------------------------------------
# EBNF -> regex (non-recursive rules, inlined)
# ----------------------------------------------------------------------

_RULE_RE = re.compile(r"^\s*([A-Za-z_][\w-]*)\s*::=\s*(.*)$")
_TOKEN_RE = re.compile(
    r"""\s*(?:
        "((?:[^"\\]|\\.)*)" |       # double-quoted terminal
        '((?:[^'\\]|\\.)*)' |       # single-quoted terminal
        (\[(?:[^\]\\]|\\.)*\]) |    # character class, passed through
        ([A-Za-z_][\w-]*)    |      # rule reference
        ([()|*+?])                  # structure
    )""", re.VERBOSE)


def ebnf_to_regex(src: str) -> str:
    rules: dict[str, str] = {}
    order: list[str] = []
    for raw in src.splitlines():
        line = raw.split("#", 1)[0].rstrip()
        if not line.strip():
            continue
        m = _RULE_RE.match(line)
        if m is None:
            raise CompileError(f"bad EBNF rule line: {line.strip()!r}")
        name, body = m.group(1), m.group(2)
        if name in rules:
            raise CompileError(f"duplicate EBNF rule {name!r}")
        rules[name] = body
        order.append(name)
    if not rules:
        raise CompileError("empty EBNF grammar")
    root = "root" if "root" in rules else order[0]
    return _ebnf_rx(root, rules, ())


def _ebnf_rx(name: str, rules: dict[str, str], stack: tuple[str, ...]) -> str:
    if name in stack:
        raise CompileError(
            f"recursive EBNF rule {name!r} (recursion is unsupported; "
            "bound the repetition explicitly)")
    if name not in rules:
        raise CompileError(f"undefined EBNF rule {name!r}")
    body = rules[name]
    out: list[str] = []
    i = 0
    while i < len(body):
        if body[i].isspace():
            i += 1
            continue
        m = _TOKEN_RE.match(body, i)
        if m is None:
            raise CompileError(f"bad EBNF token at {body[i:]!r}")
        i = m.end()
        dq, sq, cls, ref, op = m.groups()
        lit = dq if dq is not None else sq
        if lit is not None:
            text = lit.replace('\\"', '"').replace("\\'", "'")
            text = text.replace("\\n", "\n").replace("\\t", "\t")
            text = text.replace("\\\\", "\\")
            out.append("(?:" + _rx_escape(text) + ")")
        elif cls is not None:
            out.append(cls)
        elif ref is not None:
            out.append("(?:" + _ebnf_rx(ref, rules, stack + (name,)) + ")")
        else:
            out.append(op)
    return "".join(out)
