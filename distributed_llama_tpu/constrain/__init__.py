"""Grammar-constrained decoding (docs/SERVING.md "Constrained decoding").

JSON Schema / regex / EBNF grammars each lower to ONE token-level mask
automaton (automaton.py): a byte DFA over the tokenizer vocab precompiled
to per-state packed uint32 bitmask rows plus a dense transition table. The
BatchEngine stacks attached automata into a device-resident table and the
batched decode/verify scans gather+apply the mask before the split-uint32
sampler (runtime/device_loop.py, masked=True variants); GrammarProposer
walks forced-transition chains so the constraint itself drafts the
guaranteed-accept continuation (runtime/speculative.py ProposerMux).
"""

from .automaton import CompileError, TokenAutomaton
from .compiler import (byte_vocab, compile_grammar, compile_stats,
                       grammar_hash, vocab_bytes)
from .proposer import GrammarProposer
from .table import ConstraintTable

__all__ = [
    "CompileError", "TokenAutomaton", "GrammarProposer", "ConstraintTable",
    "byte_vocab", "compile_grammar", "compile_stats", "grammar_hash",
    "vocab_bytes",
]
