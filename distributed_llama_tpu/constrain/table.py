"""Device-resident stacked constraint tables (docs/SERVING.md).

Every attached grammar's local automaton is rebased into ONE pair of
fixed-capacity arrays so the masked decode/verify programs keep a stable
jit signature regardless of which grammars are co-batched:

  mask  (cap, W) uint32   per-GLOBAL-state packed allowed bitmask
  delta (cap, V) int32    GLOBAL next state per token

Row 0 is the universal state — mask all-ones, every token self-loops —
and is what unconstrained co-batched rows ride: for them the masked
program's `where(allowed, rows, NEG)` is the identity and the state
gather is loop-invariant, so their tokens are bit-identical to the
unmasked program's. Local dead transitions (-1) rebase to state 0; they
are unreachable under masked sampling (the mask already excluded the
token) and only ever indexed past a rejected verify position, where the
result is discarded.

Scheduler-thread-only (allocation at admission, release at finish); the
device upload is lazy and happens at most once per attach/detach, never
per dispatch.
"""

from __future__ import annotations

import numpy as np

from .automaton import TokenAutomaton


class ConstraintTable:
    def __init__(self, vocab_size: int, capacity: int = 512):
        self.vocab = vocab_size
        self.words = (vocab_size + 31) // 32
        self.cap = capacity
        self._mask = np.zeros((capacity, self.words), np.uint32)
        self._mask[0] = 0xFFFFFFFF
        self._delta = np.zeros((capacity, vocab_size), np.int32)
        self._regions: dict[int, tuple[int, int]] = {}  # row -> (off, n)
        self._dev = None  # (mask, delta) jnp pair, rebuilt when dirty

    @property
    def active_rows(self) -> int:
        return len(self._regions)

    def room_for(self, n_states: int) -> bool:
        return n_states <= self.cap - 1

    def alloc(self, row: int, aut: TokenAutomaton) -> int | None:
        """Rebase `aut` into a free span; returns the global offset, or
        None when the table is full (the engine degrades that row to
        unconstrained — a capacity condition, not a client error)."""
        assert row not in self._regions
        n = aut.n_states
        off = self._find_span(n)
        if off is None:
            return None
        self._mask[off:off + n] = aut.mask
        self._delta[off:off + n] = np.where(aut.delta >= 0,
                                            aut.delta + off, 0)
        self._regions[row] = (off, n)
        self._dev = None
        return off

    def free(self, row: int) -> None:
        reg = self._regions.pop(row, None)
        if reg is None:
            return
        off, n = reg
        self._mask[off:off + n] = 0
        self._delta[off:off + n] = 0
        self._dev = None

    def _find_span(self, n: int) -> int | None:
        # first-fit over the gaps between allocated regions (row 0 reserved)
        taken = sorted(self._regions.values())
        cur = 1
        for off, size in taken:
            if off - cur >= n:
                return cur
            cur = max(cur, off + size)
        return cur if self.cap - cur >= n else None

    def device(self):
        """(mask, delta) as device arrays, re-uploaded only when an
        attach/detach dirtied the host copy."""
        if self._dev is None:
            import jax.numpy as jnp

            self._dev = (jnp.asarray(self._mask), jnp.asarray(self._delta))
        return self._dev
