"""Regex -> byte DFA -> token-level mask automaton.

The supported regex subset (literals, classes, escapes, alternation,
grouping, `* + ? {m,n}` quantifiers, `.`) is compiled byte-level: a
Thompson NFA over the UTF-8 byte alphabet, subset-constructed into a DFA,
dead states pruned (a state that cannot reach acceptance disallows every
byte into it), then lowered against the tokenizer vocab by walking every
token's bytes through the DFA in lockstep. The result is a TokenAutomaton:

  mask   (S, ceil(V/32)) uint32  bit v&31 of word v>>5 = token v allowed
  delta  (S, V) int32            next state, -1 = disallowed
  forced (S,) int32              the single allowed token, -1 if not forced

State indices are LOCAL (0 = grammar start). EOS is allowed exactly at
accepting states and transitions to an absorbing `done` state (index S-1)
whose only allowed token is EOS again — a constrained row that completes
its grammar can only pad with EOS until the scheduler retires it. Every
reachable state has a non-empty mask by construction (pruning removed the
rest), so a masked argmax/sample always has a candidate.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np


class CompileError(ValueError):
    """Grammar rejected at compile time (malformed, unsupported, or too
    large) — the api edge maps this to 400 invalid_request_error."""


# maximum DFA states before lowering: bounds compile time and the device
# table row budget a single grammar can claim
MAX_DFA_STATES = 4096

_DIGITS = frozenset(range(0x30, 0x3A))
_WORD = _DIGITS | frozenset(range(0x41, 0x5B)) | frozenset(
    range(0x61, 0x7B)) | frozenset((0x5F,))
_SPACE = frozenset(b" \t\n\r\f\v")
_ALL = frozenset(range(256))
_DOT = _ALL - frozenset((0x0A,))


class _Nfa:
    def __init__(self) -> None:
        self.edges: list[list[tuple[frozenset[int], int]]] = []
        self.eps: list[set[int]] = []

    def state(self) -> int:
        self.edges.append([])
        self.eps.append(set())
        return len(self.edges) - 1


class _RegexParser:
    """Recursive-descent Thompson construction; fragments are (start, end)
    state pairs in the shared NFA builder."""

    def __init__(self, pat: str, nfa: _Nfa):
        self.pat = pat
        self.nfa = nfa
        self.i = 0

    def _peek(self) -> str:
        return self.pat[self.i] if self.i < len(self.pat) else ""

    def _take(self) -> str:
        c = self._peek()
        if not c:
            raise CompileError("unexpected end of pattern")
        self.i += 1
        return c

    def parse(self) -> tuple[int, int]:
        frag = self._alt()
        if self.i != len(self.pat):
            raise CompileError(
                f"unexpected {self.pat[self.i]!r} at {self.i}")
        return frag

    def _alt(self) -> tuple[int, int]:
        frags = [self._concat()]
        while self._peek() == "|":
            self.i += 1
            frags.append(self._concat())
        if len(frags) == 1:
            return frags[0]
        s, e = self.nfa.state(), self.nfa.state()
        for fs, fe in frags:
            self.nfa.eps[s].add(fs)
            self.nfa.eps[fe].add(e)
        return s, e

    def _concat(self) -> tuple[int, int]:
        s = self.nfa.state()
        end = s
        while self._peek() not in ("", "|", ")"):
            fs, fe = self._repeat()
            self.nfa.eps[end].add(fs)
            end = fe
        return s, end

    def _repeat(self) -> tuple[int, int]:
        start_i = self.i
        frag = self._atom()
        end_i = self.i
        c = self._peek()
        if c == "*":
            self.i += 1
            return self._star(frag)
        if c == "+":
            self.i += 1
            s, e = frag
            rs, re_ = self._star(self._reparse(start_i, end_i))
            self.nfa.eps[e].add(rs)
            return s, re_
        if c == "?":
            self.i += 1
            return self._opt(frag)
        if c == "{":
            return self._counted(frag, start_i, end_i)
        return frag

    def _reparse(self, a: int, b: int) -> tuple[int, int]:
        # counted/`+` repetition copies the atom by re-parsing its source
        # span into the shared builder (fragments cannot be cloned cheaply)
        sub = _RegexParser(self.pat[:b], self.nfa)
        sub.i = a
        frag = sub._atom()
        if sub.i != b:
            raise CompileError("malformed quantified atom")
        return frag

    def _star(self, frag: tuple[int, int]) -> tuple[int, int]:
        fs, fe = frag
        s, e = self.nfa.state(), self.nfa.state()
        self.nfa.eps[s].update((fs, e))
        self.nfa.eps[fe].update((fs, e))
        return s, e

    def _opt(self, frag: tuple[int, int]) -> tuple[int, int]:
        fs, fe = frag
        s, e = self.nfa.state(), self.nfa.state()
        self.nfa.eps[s].update((fs, e))
        self.nfa.eps[fe].add(e)
        return s, e

    def _counted(self, frag, start_i: int, end_i: int) -> tuple[int, int]:
        self.i += 1  # '{'
        spec = ""
        while self._peek() != "}":
            spec += self._take()
        self.i += 1  # '}'
        try:
            if "," in spec:
                lo_s, hi_s = spec.split(",", 1)
                lo = int(lo_s)
                hi = int(hi_s) if hi_s else -1
            else:
                lo = hi = int(spec)
        except ValueError:
            raise CompileError(f"bad quantifier {{{spec}}}") from None
        if lo < 0 or (hi >= 0 and hi < lo) or lo > 256 or hi > 256:
            raise CompileError(f"bad quantifier bounds {{{spec}}}")
        s = self.nfa.state()
        end = s
        first = frag
        for _ in range(lo):
            fs, fe = first if first is not None else self._reparse(
                start_i, end_i)
            first = None
            self.nfa.eps[end].add(fs)
            end = fe
        if hi < 0:  # {m,}: m copies then a star
            fs, fe = self._star(first if first is not None
                                else self._reparse(start_i, end_i))
            self.nfa.eps[end].add(fs)
            end = fe
        else:
            for _ in range(hi - lo):
                fs, fe = self._opt(first if first is not None
                                   else self._reparse(start_i, end_i))
                first = None
                self.nfa.eps[end].add(fs)
                end = fe
            if first is not None:  # {0}: drop the parsed atom entirely
                pass
        return s, end

    def _atom(self) -> tuple[int, int]:
        c = self._take()
        if c == "(":
            if self.pat[self.i:self.i + 2] == "?:":
                self.i += 2
            frag = self._alt()
            if self._take() != ")":
                raise CompileError("unbalanced '('")
            return frag
        if c == "[":
            return self._byteset(self._cls())
        if c == ".":
            return self._byteset(_DOT)
        if c == "\\":
            return self._escape()
        if c in "*+?{)":
            raise CompileError(f"misplaced {c!r}")
        return self._literal(c)

    def _literal(self, ch: str) -> tuple[int, int]:
        bs = ch.encode("utf-8")
        s = self.nfa.state()
        cur = s
        for b in bs:
            nxt = self.nfa.state()
            self.nfa.edges[cur].append((frozenset((b,)), nxt))
            cur = nxt
        return s, cur

    def _byteset(self, byteset: frozenset[int]) -> tuple[int, int]:
        if not byteset:
            raise CompileError("empty character class")
        s, e = self.nfa.state(), self.nfa.state()
        self.nfa.edges[s].append((byteset, e))
        return s, e

    def _escape(self) -> tuple[int, int]:
        bs = self._escape_set(self._take())
        if len(bs) == 1:
            return self._byteset(bs)
        return self._byteset(bs)

    def _escape_set(self, c: str) -> frozenset[int]:
        table = {"d": _DIGITS, "D": _ALL - _DIGITS, "w": _WORD,
                 "W": _ALL - _WORD, "s": _SPACE, "S": _ALL - _SPACE,
                 "n": frozenset((0x0A,)), "t": frozenset((0x09,)),
                 "r": frozenset((0x0D,)), "f": frozenset((0x0C,)),
                 "v": frozenset((0x0B,)), "0": frozenset((0x00,))}
        if c in table:
            return table[c]
        if c == "x":
            hx = self._take() + self._take()
            try:
                return frozenset((int(hx, 16),))
            except ValueError:
                raise CompileError(f"bad \\x escape {hx!r}") from None
        if c.isalnum():
            raise CompileError(f"unsupported escape \\{c}")
        b = c.encode("utf-8")
        if len(b) != 1:
            raise CompileError(f"non-ASCII escape \\{c}")
        return frozenset(b)

    def _cls(self) -> frozenset[int]:
        negate = False
        if self._peek() == "^":
            negate = True
            self.i += 1
        out: set[int] = set()
        first = True

        def one() -> int | None:
            # single byte, or None when the item was a multi-byte escape
            # class (\d etc) already merged into `out`
            c = self._take()
            if c == "\\":
                s = self._escape_set(self._take())
                if len(s) == 1:
                    return next(iter(s))
                out.update(s)
                return None
            b = c.encode("utf-8")
            if len(b) != 1:
                raise CompileError("non-ASCII literal in class")
            return b[0]

        while True:
            if self._peek() == "]" and not first:
                self.i += 1
                break
            first = False
            lo = one()
            if lo is None:
                continue
            if self._peek() == "-" and self.pat[self.i + 1:self.i + 2] != "]":
                self.i += 1
                hi = one()
                if hi is None or hi < lo:
                    raise CompileError("bad class range")
                out.update(range(lo, hi + 1))
            else:
                out.add(lo)
        return frozenset(_ALL - out if negate else out)


class ByteDfa:
    """Subset-constructed byte DFA with dead states pruned. `table` is a
    (S+1, 256) int32 array whose last row is an absorbing dead sentinel —
    lockstep token walks index it without branching."""

    def __init__(self, table: np.ndarray, accepting: np.ndarray):
        self.table = table  # (S, 256) int32, -1 = dead
        self.accepting = accepting  # (S,) bool

    @property
    def n_states(self) -> int:
        return self.table.shape[0]


def compile_regex_bytes(pattern: str) -> ByteDfa:
    nfa = _Nfa()
    start, accept = _RegexParser(pattern, nfa).parse()

    def closure(states: frozenset[int]) -> frozenset[int]:
        out = set(states)
        stack = list(states)
        while stack:
            for t in nfa.eps[stack.pop()]:
                if t not in out:
                    out.add(t)
                    stack.append(t)
        return frozenset(out)

    start_set = closure(frozenset((start,)))
    ids: dict[frozenset[int], int] = {start_set: 0}
    rows: list[list[int]] = []
    work = [start_set]
    while work:
        cur = work.pop()
        sid = ids[cur]
        while len(rows) <= sid:
            rows.append([-1] * 256)
        edges = [(bs, d) for s in cur for (bs, d) in nfa.edges[s]]
        by_byte: dict[int, set[int]] = {}
        for bs, d in edges:
            for b in bs:
                by_byte.setdefault(b, set()).add(d)
        for b, targets in by_byte.items():
            nxt = closure(frozenset(targets))
            nid = ids.get(nxt)
            if nid is None:
                nid = ids[nxt] = len(ids)
                if nid >= MAX_DFA_STATES:
                    raise CompileError(
                        f"grammar too large (> {MAX_DFA_STATES} DFA states)")
                work.append(nxt)
            rows[sid][b] = nid
    table = np.asarray(rows, np.int32).reshape(len(rows), 256)
    accepting = np.array([accept in s for s in
                          sorted(ids, key=ids.__getitem__)], bool)

    # prune states that cannot reach acceptance: every byte into them is
    # disallowed, so a masked sample can never paint a row into a corner
    alive = accepting.copy()
    changed = True
    while changed:
        changed = False
        reach = np.isin(table, np.flatnonzero(alive)).any(axis=1)
        grow = reach & ~alive
        if grow.any():
            alive |= grow
            changed = True
    if not alive[0]:
        raise CompileError("grammar matches no string")
    dead = ~alive
    table = np.where(np.isin(table, np.flatnonzero(dead)), -1, table)
    if dead.any():  # compact: renumber live states, drop dead rows
        remap = np.full(len(alive), -1, np.int32)
        remap[alive] = np.arange(int(alive.sum()), dtype=np.int32)
        table = table[alive]
        table = np.where(table >= 0, remap[np.clip(table, 0, None)], -1)
        accepting = accepting[alive]
    return ByteDfa(np.ascontiguousarray(table, np.int32), accepting)


@dataclass
class TokenAutomaton:
    """Token-level constraint automaton (module docstring). States are
    local; the engine's ConstraintTable rebases them when stacking."""

    mask: np.ndarray  # (S, W) uint32, W = ceil(V/32)
    delta: np.ndarray  # (S, V) int32, -1 disallowed
    forced: np.ndarray  # (S,) int32, -1 when the state is not forced
    eos_id: int
    source_hash: str = ""
    _bool_rows: dict[int, np.ndarray] = field(default_factory=dict,
                                              repr=False)

    @property
    def n_states(self) -> int:
        return self.delta.shape[0]

    @property
    def vocab_size(self) -> int:
        return self.delta.shape[1]

    def allows(self, state: int, tok: int) -> bool:
        return bool(self.delta[state, tok] >= 0)

    def advance(self, state: int, tok: int) -> int:
        return int(self.delta[state, tok])

    def mask_bool(self, state: int) -> np.ndarray:
        """(V,) bool allowed row — host-side mirror of the device gather
        (cached per state; _advance_row masks prefill-boundary logits)."""
        row = self._bool_rows.get(state)
        if row is None:
            row = (self.delta[state] >= 0)
            self._bool_rows[state] = row
        return row

    def forced_chain(self, state: int, k: int) -> list[int]:
        """Up to k tokens along singleton-mask states from `state` — the
        GrammarProposer's guaranteed-accept draft. Stops at the first
        non-forced state and does not draft past EOS."""
        out: list[int] = []
        while len(out) < k:
            f = int(self.forced[state])
            if f < 0:
                break
            out.append(f)
            if f == self.eos_id:
                break
            state = int(self.delta[state, f])
        return out

    def validate(self, tokens: list[int]) -> tuple[bool, bool]:
        """(prefix_valid, complete): walk emitted tokens; EOS terminates
        the walk and is valid only at accepting states. A max_tokens-
        truncated output is a valid prefix but not complete."""
        st = 0
        for t in tokens:
            if t == self.eos_id:
                return self.allows(st, t), self.allows(st, t)
            st = self.advance(st, t)
            if st < 0:
                return False, False
        return True, self.allows(st, self.eos_id)


def token_automaton(dfa: ByteDfa, vocab: list[bytes], eos_id: int,
                    source_hash: str = "") -> TokenAutomaton:
    """Lower a byte DFA against the vocab: walk every token's bytes from
    EVERY DFA state in lockstep (numpy-vectorized over states, one pass
    per token). Empty pieces (BOS/pad/control tokens) are disallowed
    everywhere; EOS is allowed at accepting states into the absorbing
    `done` state."""
    sd = dfa.n_states
    if not (0 <= eos_id < len(vocab)):
        raise CompileError(f"eos id {eos_id} outside vocab")
    # sentinel dead row: index sd maps every byte to itself
    ext = np.vstack([np.where(dfa.table >= 0, dfa.table, sd).astype(np.int32),
                     np.full((1, 256), sd, np.int32)])
    v = len(vocab)
    done = sd  # absorbing post-EOS state
    delta = np.full((sd + 1, v), -1, np.int32)
    base = np.arange(sd, dtype=np.int32)
    for t, piece in enumerate(vocab):
        if t == eos_id or not piece:
            continue
        sv = base
        for b in piece:
            sv = ext[sv, b]
        delta[:sd, t] = np.where(sv < sd, sv, -1)
    delta[np.flatnonzero(dfa.accepting), eos_id] = done
    delta[done, eos_id] = done
    allowed = delta >= 0
    if not allowed[:sd].any(axis=1).all():
        # pruning guarantees byte-level liveness; a vocab that cannot spell
        # any continuation byte still strands the state — reject honestly
        raise CompileError("vocab cannot spell the grammar (empty mask row)")
    w = (v + 31) // 32
    padded = np.zeros((sd + 1, w * 32), bool)
    padded[:, :v] = allowed
    mask = (padded.reshape(sd + 1, w, 32).astype(np.uint32)
            << np.arange(32, dtype=np.uint32)).sum(axis=2, dtype=np.uint32)
    counts = allowed.sum(axis=1)
    forced = np.where(counts == 1, allowed.argmax(axis=1), -1).astype(np.int32)
    return TokenAutomaton(mask=mask, delta=delta, forced=forced,
                          eos_id=eos_id, source_hash=source_hash)


def regex_token_automaton(pattern: str, vocab: list[bytes], eos_id: int,
                          source_hash: str = "") -> TokenAutomaton:
    return token_automaton(compile_regex_bytes(pattern), vocab, eos_id,
                           source_hash)
