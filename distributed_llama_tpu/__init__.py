"""distributed_llama_tpu — TPU-native distributed LLM inference framework.

A ground-up rebuild of the capabilities of `distributed-llama` (C++/TCP tensor-parallel
CPU inference) as a single-program SPMD JAX/XLA system on TPU meshes. See SURVEY.md for
the reference blueprint and the mapping from its layers to this package.
"""

__version__ = "0.1.0"

from .quants import FloatType, QTensor  # noqa: F401
