"""distributed_llama_tpu — TPU-native distributed LLM inference framework.

A ground-up rebuild of the capabilities of `distributed-llama` (C++/TCP tensor-parallel
CPU inference) as a single-program SPMD JAX/XLA system on TPU meshes. See SURVEY.md for
the reference blueprint and the mapping from its layers to this package.
"""

__version__ = "0.1.0"

__all__ = ["FloatType", "QTensor"]


def __getattr__(name: str):
    # lazy re-exports (PEP 562): importing the package must not pull in
    # quants/jax — the fleet router (apps/router.py) is a pure-stdlib process
    # that imports distributed_llama_tpu.fleet without ever loading a device
    # runtime
    if name in __all__:
        from . import quants

        return getattr(quants, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
