"""Docs-drift lints (rules `metric-docs`, `fault-docs`).

Two hand-maintained inventories rot silently unless machine-checked:

- **metric-docs** (migrated from perf/smoke_lint.py, where its first run
  found 6 undocumented metrics): every literal-named
  `metrics.counter/gauge/histogram(...)` registration in the package must
  appear in docs/OBSERVABILITY.md as a delimited token.
- **fault-docs** (new): every `faults.fire("point", ...)` injection point in
  the package must appear in docs/ROBUSTNESS.md's injection-point inventory
  — the inventory has been hand-extended across PRs 4/6/8/9 and a point
  missing from it is invisible to operators writing DLLAMA_FAULTS configs
  and to the fault-matrix reviewers.

Both match the doc as a DELIMITED token, not a substring: `prefix_cache_hit`
must not ride on `prefix_cache_hit_tokens_total`.
"""

from __future__ import annotations

import ast
import os
import re

from .core import REPO, Finding, Source

_METRIC_FACTORIES = ("counter", "gauge", "histogram")
OBS_DOC = os.path.join(REPO, "docs", "OBSERVABILITY.md")
ROBUSTNESS_DOC = os.path.join(REPO, "docs", "ROBUSTNESS.md")


def _delimited(token: str, doc: str) -> bool:
    return re.search(r"(?<![A-Za-z0-9_.])" + re.escape(token)
                     + r"(?![A-Za-z0-9_])", doc) is not None


def _package_sources(sources: list[Source]) -> list[Source]:
    pkg = "distributed_llama_tpu" + os.sep
    return [s for s in sources if s.relpath.startswith(pkg)]


# ----------------------------------------------------------------------
# metric registrations
# ----------------------------------------------------------------------

def collect_metric_registrations(sources: list[Source],
                                 package_only: bool = True
                                 ) -> list[tuple[str, str, int]]:
    """[(metric name, relpath, line)] for every literal-named
    counter()/gauge()/histogram() call in the package sources. Matches both
    module conveniences (`metrics.counter(...)`) and registry methods
    (`REGISTRY.counter(...)`) by attribute name, and bare-name calls after a
    from-import by function name; non-literal first args are skipped (none
    exist today, and a dynamic name needs its own doc story anyway)."""
    out = []
    for src in (_package_sources(sources) if package_only else sources):
        if src.tree is None:
            continue
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Call) or not node.args:
                continue
            fn = node.func
            name = (fn.attr if isinstance(fn, ast.Attribute)
                    else fn.id if isinstance(fn, ast.Name) else None)
            if name not in _METRIC_FACTORIES:
                continue
            first = node.args[0]
            if isinstance(first, ast.Constant) and isinstance(first.value,
                                                              str):
                out.append((first.value, src.relpath, node.lineno))
    return sorted(set(out))


def check_metric_docs(sources: list[Source],
                      doc_path: str = OBS_DOC) -> list[Finding]:
    try:
        with open(doc_path, encoding="utf-8") as fh:
            doc = fh.read()
    except OSError:
        return [Finding("metric-docs", os.path.relpath(doc_path, REPO), 0,
                        "missing — the metric inventory has nowhere to live")]
    return [Finding("metric-docs", path, line,
                    f"metric '{name}' is not documented in "
                    "docs/OBSERVABILITY.md (add it to the inventory)")
            for name, path, line in collect_metric_registrations(sources)
            if not _delimited(name, doc)]


# ----------------------------------------------------------------------
# fault injection points
# ----------------------------------------------------------------------

def collect_fault_points(sources: list[Source]) -> list[tuple[str, str, int]]:
    """[(point name, relpath, line)] for every literal-named
    `faults.fire("...")` (or bare `fire("...")` after a from-import) in the
    package. The framework's own module is excluded — its `fire` definitions
    and docstrings are not injection points."""
    out = []
    for src in _package_sources(sources):
        if src.tree is None or src.relpath.endswith(
                os.path.join("resilience", "faults.py")):
            continue
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Call) or not node.args:
                continue
            fn = node.func
            name = (fn.attr if isinstance(fn, ast.Attribute)
                    else fn.id if isinstance(fn, ast.Name) else None)
            if name != "fire":
                continue
            first = node.args[0]
            if isinstance(first, ast.Constant) and isinstance(first.value,
                                                              str):
                out.append((first.value, src.relpath, node.lineno))
    return sorted(set(out))


def check_fault_docs(sources: list[Source],
                     doc_path: str = ROBUSTNESS_DOC) -> list[Finding]:
    try:
        with open(doc_path, encoding="utf-8") as fh:
            doc = fh.read()
    except OSError:
        return [Finding("fault-docs", os.path.relpath(doc_path, REPO), 0,
                        "missing — the injection-point inventory has "
                        "nowhere to live")]
    return [Finding("fault-docs", path, line,
                    f"fault point '{point}' is not documented in "
                    "docs/ROBUSTNESS.md's injection-point inventory")
            for point, path, line in collect_fault_points(sources)
            if not _delimited(point, doc)]
