"""Repo-wide syntax + dead-import passes (rules `compile`, `dead-import`).

Migrated from perf/smoke_lint.py (which remains as a thin shim so the
tier-1 test names don't churn):

- **compile** — byte-compiles every first-party .py, so a syntax error in a
  rarely-imported app path (the class of defect that survives a test suite
  importing only what it tests) fails tier-1 instead of the first prod run.
- **dead-import** — pyflakes when available; otherwise a conservative AST
  fallback: an import-bound name is flagged only when its identifier appears
  NOWHERE else in the file text (docstrings and `__all__` strings count as
  uses, `# noqa` on the import line opts out), so false positives are
  structurally impossible for any name the file mentions at all.
"""

from __future__ import annotations

import ast
import compileall
import os
import re

from .core import REPO, Finding, Source


def check_compile(files: list[str], repo: str = REPO) -> list[Finding]:
    findings = []
    for f in files:
        # quiet=2 silences listings; failure prints to stderr AND returns False
        if not compileall.compile_file(f, quiet=2, force=False):
            findings.append(Finding("compile", os.path.relpath(f, repo), 0,
                                    "failed to byte-compile"))
    return findings


def _pyflakes_check(files: list[str],
                    repo: str = REPO) -> list[Finding] | None:
    """Full pyflakes run when the tool is importable; None = unavailable."""
    try:
        from pyflakes.api import checkPath
        from pyflakes.reporter import Reporter
    except ImportError:
        return None
    import io

    out, err = io.StringIO(), io.StringIO()
    rep = Reporter(out, err)
    n = 0
    for f in files:
        n += checkPath(f, rep)
    if n == 0:
        return []
    findings = []
    for ln in (out.getvalue() + err.getvalue()).splitlines():
        # only unused-import findings gate; other pyflakes classes advisory
        if "imported but unused" not in ln:
            continue
        m = re.match(r"([^:]+):(\d+):(?:\d+:)?\s*(.*)", ln)
        if m:
            findings.append(Finding(
                "dead-import", os.path.relpath(m.group(1), repo),
                int(m.group(2)), m.group(3)))
        else:
            findings.append(Finding("dead-import", ln, 0, ln))
    return findings


def fallback_dead_imports(source: Source) -> list[Finding]:
    """Names bound by import statements that the file never mentions again."""
    if os.path.basename(source.path) == "__init__.py":
        return []  # re-export surface: unused-looking imports are the point
    if source.tree is None:
        return []  # the compile pass reports this
    findings = []
    bound: list[tuple[str, int]] = []
    for node in ast.walk(source.tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                bound.append(((a.asname or a.name.split(".")[0]),
                              node.lineno))
        elif isinstance(node, ast.ImportFrom):
            if node.module == "__future__":
                continue
            for a in node.names:
                if a.name == "*":
                    continue
                bound.append(((a.asname or a.name), node.lineno))
    for name, lineno in bound:
        if "noqa" in source.line_text(lineno):
            continue
        # a name is "used" if it appears anywhere else in the file at all
        # (code, strings, __all__, docstrings) — maximally conservative
        uses = len(re.findall(rf"\b{re.escape(name)}\b", source.text))
        if uses <= 1:
            findings.append(Finding("dead-import", source.relpath, lineno,
                                    f"'{name}' imported but unused"))
    return findings


def check_dead_imports(sources: list[Source],
                       repo: str = REPO) -> list[Finding]:
    via_pyflakes = _pyflakes_check([s.path for s in sources], repo)
    if via_pyflakes is not None:
        return via_pyflakes
    findings: list[Finding] = []
    for s in sources:
        findings.extend(fallback_dead_imports(s))
    return findings
