"""Compile-manifest gate (rule `compile-manifest`): recompile-creep auditor.

A TPU serving process must settle into a FIXED set of compiled programs —
the forward step per window bucket, the K-step scan per (k, mode), the
verify block per (T, mode) — each dispatched at a fixed set of array
shapes/dtypes. Recompile creep (a new T bucket minted on the latency path, a
dtype drifting through a refactor, a shape leaking per-request) is invisible
to unit tests and BENCH_r03/r04-class expensive on hardware: XLA compiles
mid-traffic and the request eating the compile times out.

This auditor is runtime-assisted: `CompileAudit` patches the program
factories (`make_sharded_forward`, `make_decode_loop`,
`make_batched_decode_loop`, `make_batched_verify_loop`) to record

  - every PROGRAM BUILD, keyed by factory + static config
    (e.g. ``batched_scan[k=4,mode=greedy,window=None]``), and
  - every DISPATCH SIGNATURE per program — the (dtype, shape) tuple of each
    array argument (list args by length) — since jit caches per abstract
    value, each distinct signature is a distinct XLA lowering.

`run_scenario` drives the real BatchEngine through a fixed tiny-model
script: prefill (8+1 chunks), K-step scans, pipelined chaining, draft-verify
blocks, a stochastic row, and a durable-resume admission. The observed
manifest is diffed against the pinned ``perf/compile_manifest.json``:

  - a program key absent from the pin  -> finding (new compiled program)
  - a signature absent under its key   -> finding (new dispatch shape)
  - observed ⊂ pinned                  -> ok (scheduling may not exercise
    every pinned shape on every run; the gate is one-sided by design)

When a new dispatch shape is INTENTIONAL (a new feature legitimately adds a
program), re-pin with ``python perf/dlint.py --update-manifest`` and review
the manifest diff like any other lockfile (docs/ANALYSIS.md).
"""

from __future__ import annotations

import json
import os
from contextlib import ExitStack

from .core import REPO, Finding

MANIFEST_PATH = os.path.join(REPO, "perf", "compile_manifest.json")
_MANIFEST_REL = os.path.join("perf", "compile_manifest.json")


def _describe(a) -> str:
    """Compact, stable descriptor of one dispatch argument."""
    if hasattr(a, "shape") and hasattr(a, "dtype"):
        return f"{a.dtype}{tuple(a.shape)}"
    if isinstance(a, (list, tuple)):
        if a and isinstance(a[0], (list, tuple)):
            return f"list({len(a)}x{len(a[0])})"
        return f"list({len(a)})"
    if isinstance(a, dict):
        return "tree"
    if isinstance(a, (bool, int, float)):
        return type(a).__name__
    return type(a).__name__


class CompileAudit:
    """Records program builds + dispatch signatures while active (a context
    manager patching the factory modules; nesting is not supported)."""

    def __init__(self):
        # key -> {"builds": int, "signatures": set[str]}
        self.programs: dict[str, dict] = {}
        self._stack: ExitStack | None = None

    # -- recording ------------------------------------------------------

    def _program(self, key: str) -> dict:
        if key not in self.programs:
            self.programs[key] = {"builds": 0, "signatures": set()}
        return self.programs[key]

    def record_build(self, key: str) -> None:
        self._program(key)["builds"] += 1

    def record_call(self, key: str, args: tuple) -> None:
        sig = " ".join(_describe(a) for a in args)
        self._program(key)["signatures"].add(sig)

    def _wrap(self, key: str, fn):
        def wrapped(*args, **kw):
            self.record_call(key, args)
            return fn(*args, **kw)

        return wrapped

    def _patch_factory(self, module, name: str, keyfn):
        orig = getattr(module, name)

        def factory(*args, **kw):
            key = keyfn(*args, **kw)
            self.record_build(key)
            return self._wrap(key, orig(*args, **kw))

        setattr(module, name, factory)
        self._stack.callback(setattr, module, name, orig)

    # -- lifecycle ------------------------------------------------------

    def __enter__(self) -> "CompileAudit":
        from ..runtime import device_loop, engine

        self._stack = ExitStack()

        def _paged(kw):
            # device-resident paged KV (docs/PAGED_KV.md): the block size is
            # part of the cache key — a paged program's table/pool shapes
            # are distinct lowerings from the dense layout's
            bt = kw.get("kv_block_tokens", 0)
            return f",paged={bt}" if bt else ""

        def _kern(kw):
            # kernel-policy dimension (ops/matmul.py): the STRING policies
            # ("all", "fused") change which programs lower — a fused engine's
            # T buckets are distinct lowerings from the XLA ones and must be
            # pinned separately. Boolean policies add nothing, so every
            # pre-existing pinned key is unchanged.
            up = kw.get("use_pallas")
            return f",kernel={up}" if isinstance(up, str) else ""

        def _mask(kw):
            # grammar-constrained variants (constrain/, docs/SERVING.md
            # "Constrained decoding"): masked programs are SEPARATE
            # lowerings (constraint-table operands + automaton carry) and
            # pin under their own keys. Boolean policy: the default
            # (unmasked) adds nothing, so every pre-existing pinned key is
            # unchanged.
            return ",mask=1" if kw.get("masked") else ""

        def _static(kw):
            return (f"mode={kw.get('mode', 'greedy')},"
                    f"window={kw.get('attn_window')}"
                    f"{_paged(kw)}{_kern(kw)}{_mask(kw)}")

        self._patch_factory(
            engine, "make_sharded_forward",
            lambda spec, mesh, params, **kw:
                f"forward_step[window={kw.get('attn_window')}"
                f"{_paged(kw)}{_kern(kw)}]")
        self._patch_factory(
            device_loop, "make_decode_loop",
            lambda spec, mesh, params, n, **kw:
                f"decode_loop[n={n},{_static(kw)}]")
        self._patch_factory(
            device_loop, "make_batched_decode_loop",
            lambda spec, mesh, params, n, **kw:
                f"batched_scan[k={n},{_static(kw)}]")
        self._patch_factory(
            device_loop, "make_batched_verify_loop",
            lambda spec, mesh, params, t, **kw:
                f"verify[t={t},{_static(kw)}]")
        # model drafter programs (draft/, docs/SERVING.md "Model-based
        # drafting") — patched at the DRAFTER's namespace (its module-global
        # names bound at import, like engine.make_sharded_forward above)
        from ..draft import drafter as draft_drafter

        self._patch_factory(
            draft_drafter, "make_draft_loop",
            lambda spec, mesh, params, s, **kw:
                f"draft_scan[s={s}{_kern(kw)}]")
        self._patch_factory(
            draft_drafter, "make_draft_step",
            lambda spec, mesh, params, **kw:
                f"draft_step[window={kw.get('attn_window')}{_kern(kw)}]")
        return self

    def __exit__(self, *exc) -> None:
        self._stack.close()
        self._stack = None

    # -- export ---------------------------------------------------------

    def manifest(self) -> dict:
        return {"programs": {
            key: {"builds": rec["builds"],
                  "signatures": sorted(rec["signatures"])}
            for key, rec in sorted(self.programs.items())}}


# ----------------------------------------------------------------------
# the fixed scenario script
# ----------------------------------------------------------------------

def scenario_spec():
    """Tiny 2-layer model, seq_len 64 (< the window-bucket floor, so exactly
    one forward-step window compiles) — the same scale the spec-amortize and
    fault-matrix tier-1 gates run at."""
    from ..models.spec import ArchType, ModelSpec, RopeType

    return ModelSpec(arch_type=ArchType.LLAMA, dim=64, hidden_dim=128,
                     n_layers=2, n_heads=4, n_kv_heads=4, vocab_size=256,
                     seq_len=64, rope_type=RopeType.LLAMA).resolved()


def run_scenario(keep_engine: bool = False):
    """Drive the real BatchEngine through every serving phase the manifest
    pins: prefill (8+1 chunks), greedy K-step scans with pipelined chaining,
    a stochastic scan row, draft-verify blocks on a repetitive prompt, and a
    durable-resume admission (which must reuse the existing programs, not
    mint new ones). Deterministic by construction: fixed prompts, fixed
    seeds, phases serialized by wait()."""
    from ..models.params import init_random_params
    from ..quants import FloatType
    from ..runtime.batch_engine import BatchEngine
    from ..runtime.sampler import Sampler

    spec = scenario_spec()
    params = init_random_params(spec, FloatType.Q40, seed=11)
    eng = BatchEngine(spec, params, slots=2, superstep=4, pipeline=True,
                      speculative=4, spec_min_draft=1, tp=1,
                      prefix_cache=True)
    V = spec.vocab_size
    ok = False
    try:
        # phase 1 — prefill + greedy scans + pipelined chain: two co-batched
        # greedy requests; 9-token prompts prefill as one 8-chunk + one
        # 1-chunk; 12 decode tokens at k=4 exercise chained super-steps.
        # Non-repetitive prompts keep the n-gram drafts empty (scan path).
        p1 = [(7 * i + 3) % V for i in range(9)]
        p2 = [(11 * i + 5) % V for i in range(9)]
        r1 = eng.submit(p1, 12, Sampler(V))
        r2 = eng.submit(p2, 12, Sampler(V))
        r1.wait(60)
        r2.wait(60)
        # phase 2 — stochastic scan: one seeded sampled request alone, so
        # the sample-mode scan program (and its rng upload shape) pins.
        rs = eng.submit(p1, 8, Sampler(V, temperature=0.8, seed=7))
        out_s = rs.wait(60)
        # phase 3 — draft-verify: a repetitive prompt makes the per-slot
        # NgramIndex propose full drafts, engaging the (B, T) verify blocks.
        rep = [9, 21, 33] * 6
        rv = eng.submit(rep, 12, Sampler(V))
        rv.wait(60)
        # phase 4 — durable resume: re-admit phase 2's request as a
        # mid-stream failover would (prompt ⊕ delivered, fast-forwarded
        # sampler). Resume is an ADMISSION property: it must ride the
        # existing prefill/scan programs — a resume-only program key in the
        # manifest diff is itself the defect this phase exists to catch.
        smp = Sampler(V, temperature=0.8, seed=7)
        smp.fast_forward(len(out_s))
        rr = eng.submit(p1 + out_s, 6, smp, resume_tokens=len(out_s))
        rr.wait(60)
        # phase 5 — paged remap admission (docs/PAGED_KV.md): re-admit a
        # directory-covered prompt so the zero-copy block-table remap path
        # runs. Remap is table METADATA only — it must ride the existing
        # prefill/scan programs at their pinned signatures; a remap-shaped
        # program key or a table-shape drift here is exactly the
        # block-table recompile creep this gate exists to catch.
        rm = eng.submit(list(p2), 6, Sampler(V))
        rm.wait(60)
        # phase 6 — disaggregation import-seeded admission (docs/DISAGG.md):
        # a NEVER-SERVED prompt whose KV "arrives over the wire"
        # (import_kv_blocks → cold directory nodes, round-tripped through
        # the codec like a real transfer) and is promoted to device at
        # admission. The import is host bookkeeping and the promotion rides
        # the untracked single-block pool update; the admission itself must
        # ride the existing prefill/scan programs — an import-shaped
        # program key or signature here is disagg-induced recompile creep.
        if eng.kv_pool is not None:
            import numpy as _np

            from ..cache.wire import decode_blocks, encode_blocks

            bt = eng._kv_bt
            p3 = [(13 * i + 2) % V for i in range(bt + 1)]  # 1 full block
            L, _n, hk, _bt, hs = eng._eng.k_cache.shape
            rng = _np.random.default_rng(3)
            blocks = [(rng.standard_normal((L, hk, bt, hs))
                       .astype(_np.float32),
                       rng.standard_normal((L, hk, bt, hs))
                       .astype(_np.float32))]
            eng.import_kv_blocks(p3[:bt], decode_blocks(
                encode_blocks(blocks)))
            ri = eng.submit(list(p3), 4, Sampler(V))
            ri.wait(60)
        # phase 7 — model-based drafting (docs/SERVING.md "Model-based
        # drafting"): a SECOND engine, identical config plus a co-resident
        # drafter sharing the target's params (self-draft: full acceptance,
        # so the drafter's scan cadence — and thus the pinned draft_scan
        # bucket set — is deterministic). Target-side programs ride the
        # same keys/signatures the first engine pinned; the drafter adds
        # ONLY draft_scan[s=...] buckets. Adaptive-k runs live here — its
        # buckets must never mint a verify program outside the pinned
        # t=2/3/5 set (the "zero recompile creep under adaptive-k bucket
        # churn" acceptance gate).
        eng2 = BatchEngine(spec, params, slots=2, superstep=4, pipeline=True,
                           speculative=4, spec_min_draft=1, tp=1,
                           prefix_cache=True,
                           draft_model=(spec, params))
        try:
            rd = eng2.submit([(7 * i + 3) % V for i in range(9)], 12,
                             Sampler(V))
            rd.wait(60)
            rd2 = eng2.submit([(5 * i + 1) % V for i in range(6)], 8,
                              Sampler(V))
            rd2.wait(60)
            # long prompt: attach-time pending exceeds the in-scan catch-up
            # cap, so the drafter's chunked prefill program (draft_step)
            # pins alongside the scan buckets
            rd3 = eng2.submit([(3 * i + 2) % V for i in range(20)], 6,
                              Sampler(V))
            rd3.wait(60)
        finally:
            eng2.close()
        # phase 8 — fused-kernel policy (ops/pallas_q4_mm.py, --fused-matmul):
        # a THIRD engine with use_pallas upgraded to "fused", so every program
        # the batched serving path builds under the kernel policy pins under
        # its own `kernel=fused` key (the string policy is part of the jit
        # cache key by construction: different lowerings, different programs).
        # The co-resident self-drafter makes verify engagement deterministic
        # for ANY prompt (n-gram proposals on a fresh engine are not) and
        # pins the drafter's own fused draft_scan/draft_step buckets; the
        # reachable T buckets must stay inside the kernel-off t=2/3/5 set —
        # a fused key minting a rogue T bucket fails the gate by name.
        eng3 = BatchEngine(spec, params, slots=2, superstep=4, pipeline=True,
                           speculative=4, spec_min_draft=1, tp=1,
                           use_pallas=True, fused_matmul=True,
                           draft_model=(spec, params))
        try:
            rf1 = eng3.submit(p1, 12, Sampler(V))
            rf2 = eng3.submit(p2, 12, Sampler(V))
            rf1.wait(60)
            rf2.wait(60)
            # seeded stochastic row: sample-mode scan + verify under the
            # kernel key (the greedy/sample × kernel-on cross)
            rfs = eng3.submit(p1, 8, Sampler(V, temperature=0.8, seed=7))
            rfs.wait(60)
            rfv = eng3.submit(rep, 12, Sampler(V))
            rfv.wait(60)
        finally:
            eng3.close()
        # phase 9 — grammar-constrained decoding (constrain/,
        # docs/SERVING.md "Constrained decoding"): constrained rows
        # co-batched with a plain row on a FOURTH engine, greedy AND
        # seeded-stochastic, with speculation on so the GrammarProposer's
        # forced chains engage the masked verify buckets. Masked programs
        # pin under their own mask=1 keys (separate lowerings: constraint
        # table operands + automaton carry); the unmasked keys must stay
        # untouched — a masked dispatch minting a bucket outside the
        # pinned t set, or leaking onto an unmasked key, fails the gate
        # by name.
        from ..constrain import byte_vocab, compile_grammar

        cv = byte_vocab(V)
        aut, gh = compile_grammar(
            "json_schema",
            {"type": "object", "properties": {
                "name": {"enum": ["alpha", "beta"]},
                "ok": {"type": "boolean"}}}, cv, eos_id=2)
        eng4 = BatchEngine(spec, params, slots=2, superstep=4,
                           pipeline=True, speculative=4, spec_min_draft=1,
                           tp=1, prefix_cache=True)
        try:
            rc1 = eng4.submit(p1, 12, Sampler(V), constraint=aut,
                              constraint_hash=gh)
            rc2 = eng4.submit(rep, 12, Sampler(V))  # plain co-batched row
            rc1.wait(60)
            rc2.wait(60)
            rcs = eng4.submit(p2, 10, Sampler(V, temperature=0.8, seed=7),
                              constraint=aut, constraint_hash=gh)
            rcs.wait(60)
            # a branching-only grammar (no singleton-mask states, so the
            # GrammarProposer never drafts and n-gram finds nothing on a
            # fresh prompt): constrained rows ride the masked K-step SCAN
            # buckets — greedy and sampled — instead of verify
            aut2, gh2 = compile_grammar("regex", "[a-z]{24}", cv, eos_id=2)
            rm1 = eng4.submit(p1, 10, Sampler(V), constraint=aut2,
                              constraint_hash=gh2)
            rm1.wait(60)
            rm2 = eng4.submit(p2, 8, Sampler(V, temperature=0.8, seed=7),
                              constraint=aut2, constraint_hash=gh2)
            rm2.wait(60)
        finally:
            eng4.close()
        ok = True
    finally:
        # a failed phase must not leak a live engine (scheduler thread +
        # params + KV caches for the rest of the process) — keep_engine
        # hands the engine out only on success
        if not keep_engine or not ok:
            eng.close()
    return eng if keep_engine else None


# ----------------------------------------------------------------------
# manifest diff / pin
# ----------------------------------------------------------------------

def load_manifest(path: str | None = None) -> dict | None:
    path = path or MANIFEST_PATH
    try:
        with open(path, encoding="utf-8") as fh:
            return json.load(fh)
    except OSError:
        return None


def diff_manifest(observed: dict, pinned: dict | None) -> list[Finding]:
    """Findings for every observed program/signature the pin does not cover.
    One-sided: pinned-but-unobserved entries are fine (scheduling may skip
    shapes on a given run)."""
    if pinned is None:
        return [Finding("compile-manifest", _MANIFEST_REL, 0,
                        "pinned manifest missing — run "
                        "`python perf/dlint.py --update-manifest`")]
    pinned_programs = pinned.get("programs", {})
    findings = []
    for key, rec in sorted(observed.get("programs", {}).items()):
        pin = pinned_programs.get(key)
        if pin is None:
            findings.append(Finding(
                "compile-manifest", _MANIFEST_REL, 0,
                f"recompile creep: program {key} compiled but is not in the "
                "pinned manifest (new cache key; if intentional, re-pin "
                "with `python perf/dlint.py --update-manifest`)"))
            continue
        known = set(pin.get("signatures", []))
        for sig in sorted(rec["signatures"]):
            if sig not in known:
                findings.append(Finding(
                    "compile-manifest", _MANIFEST_REL, 0,
                    f"recompile creep: program {key} dispatched at a new "
                    f"signature [{sig}] — a fresh XLA lowering on the "
                    "serving path (shape leak or dtype drift; if "
                    "intentional, re-pin)"))
    return findings


def check_manifest(manifest_path: str | None = None) -> list[Finding]:
    """Run the scenario under audit and diff against the pin (the
    `compile_gate=True` arm of analysis/runner.py)."""
    audit = CompileAudit()
    with audit:
        run_scenario()
    return diff_manifest(audit.manifest(), load_manifest(manifest_path))


def update_manifest(path: str | None = None) -> dict:
    """Re-run the scenario and pin the observed manifest. The diff against
    the previous pin is MERGED (union), never shrunk implicitly: shapes a
    particular run didn't exercise must not silently fall out of the pin —
    delete retired programs by hand, with review."""
    path = path or MANIFEST_PATH
    audit = CompileAudit()
    with audit:
        run_scenario()
    observed = audit.manifest()
    prev = load_manifest(path)
    if prev is not None:
        for key, rec in prev.get("programs", {}).items():
            mine = observed["programs"].setdefault(
                key, {"builds": rec.get("builds", 0), "signatures": []})
            mine["signatures"] = sorted(
                set(mine["signatures"]) | set(rec.get("signatures", [])))
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(observed, fh, indent=2, sort_keys=True)
        fh.write("\n")
    return observed
