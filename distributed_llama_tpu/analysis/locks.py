"""Lock-discipline checker (rules `lock-guard`, `lock-blocking`).

The serving stack's concurrency correctness is hand-maintained: the
BatchEngine scheduler, HTTP handler threads, the membership poller, and the
flight recorder all share mutable state behind plain `threading.Lock`s, and
nothing verified the discipline until a race reached hardware. This pass
machine-checks two invariants the reviewers previously re-derived by hand:

1. **lock-guard** — an attribute declared guarded (a `# guards: a, b`
   comment on the line creating the lock, e.g.
   `self._plock = threading.Lock()  # guards: _pending`) may only be read or
   written inside the owning class under a lexical `with self.<lock>:`
   block, or in a method annotated `# holds: self.<lock>`. `__init__` is
   exempt (construction happens-before publication). Accesses from OUTSIDE
   the class are out of scope — the convention is per-class ownership.

2. **lock-blocking** — while any of the class's declared locks is lexically
   held, calls that can block indefinitely are flagged: `time.sleep`,
   zero-positional-arg `.join()` (Thread/Process join — `",".join(xs)`
   passes an iterable and is ignored), `.getresponse()` / `.request()` /
   `urlopen` / `socket.*` connection traffic, `.accept()` / `.recv()`,
   `.block_until_ready()`, `np.asarray` on device arrays can't be told
   apart syntactically so it is left to the hot-path pass, `.wait()` on
   anything that is NOT the held lock itself (`Condition.wait` RELEASES the
   lock it is called on and is the correct idiom), `open()` and queue
   `.get()` with no `_nowait`. This is the exact bug class behind scheduler
   stalls: one slow HTTP read under the membership lock stalls every router
   thread.

Both rules are triaged per finding: real ones get fixed, benign ones carry
`# dlint: ignore[rule] -- reason` (analysis/core.py).
"""

from __future__ import annotations

import ast
import re

from .core import Finding, Source, comment_on, marker_on

_LOCK_TYPES = ("Lock", "RLock", "Condition")
_GUARDS_RE = re.compile(r"#\s*guards:\s*([A-Za-z0-9_,.\s]+)")
_HOLDS_RE = re.compile(r"#\s*holds:\s*([A-Za-z0-9_,.\s]+)")

# blocking call names matched on the ATTRIBUTE (x.<name>(...)) or bare name
_BLOCKING_ATTRS = {"getresponse", "accept", "recv", "block_until_ready",
                   "urlopen", "request", "connect", "sendall"}
_BLOCKING_BARE = {"urlopen", "open", "input"}


def _is_lock_ctor(node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    fn = node.func
    name = (fn.attr if isinstance(fn, ast.Attribute)
            else fn.id if isinstance(fn, ast.Name) else None)
    return name in _LOCK_TYPES


def _self_attr(node: ast.AST) -> str | None:
    """'x' for an ast node `self.x`, else None."""
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


def _names(raw: str) -> list[str]:
    return [n.strip().removeprefix("self.")
            for n in raw.split(",") if n.strip()]


class _ClassLocks:
    """Lock declarations of one class: {lock attr: [guarded attrs]}."""

    def __init__(self):
        self.locks: dict[str, list[str]] = {}

    @property
    def guarded(self) -> dict[str, str]:
        return {a: lk for lk, attrs in self.locks.items() for a in attrs}


def _is_lock_field(node: ast.AST) -> bool:
    """dataclass-style `x: Lock = field(default_factory=threading.Lock)`."""
    if not isinstance(node, ast.Call):
        return False
    fn = node.func
    name = (fn.attr if isinstance(fn, ast.Attribute)
            else fn.id if isinstance(fn, ast.Name) else None)
    if name != "field":
        return False
    for kw in node.keywords:
        if kw.arg == "default_factory":
            fac = kw.value
            fac_name = (fac.attr if isinstance(fac, ast.Attribute)
                        else fac.id if isinstance(fac, ast.Name) else None)
            return fac_name in _LOCK_TYPES
    return False


def _collect_locks(source: Source, cls: ast.ClassDef) -> _ClassLocks:
    out = _ClassLocks()
    for node in ast.walk(cls):
        if isinstance(node, ast.Assign) and _is_lock_ctor(node.value):
            targets = node.targets
        elif (isinstance(node, ast.AnnAssign) and node.value is not None
              and isinstance(node.target, ast.Name)
              and (_is_lock_ctor(node.value)
                   or _is_lock_field(node.value))):
            # dataclass field declaration: the target is a bare class-level
            # name, which becomes `self.<name>` at runtime
            out.locks[node.target.id] = _guards_at(source, node.lineno)
            continue
        else:
            continue
        for tgt in targets:
            attr = _self_attr(tgt)
            if attr is None:
                continue
            out.locks[attr] = _guards_at(source, node.lineno)
    return out


def _guards_at(source: Source, lineno: int) -> list[str]:
    m = _GUARDS_RE.search(comment_on(source, lineno))
    return _names(m.group(1)) if m else []


class _MethodChecker(ast.NodeVisitor):
    def __init__(self, source: Source, cls_name: str, locks: _ClassLocks,
                 held_at_entry: set[str], findings: list[Finding]):
        self.source = source
        self.cls_name = cls_name
        self.locks = locks
        self.guarded = locks.guarded
        self.held: set[str] = set(held_at_entry)
        self.findings = findings

    # -- lock tracking --------------------------------------------------

    def visit_With(self, node: ast.With) -> None:
        acquired = []
        for item in node.items:
            # `with self._lock:` and `with self._lock, other:` forms; also
            # `with self._cond:` (Condition acquires its lock). Helper forms
            # (`with self._lock.something():`) are not recognized — the
            # convention is plain `with lock`.
            attr = _self_attr(item.context_expr)
            if attr in self.locks.locks and attr not in self.held:
                acquired.append(attr)
                self.held.add(attr)
        for item in node.items:
            self.visit(item.context_expr)
        for stmt in node.body:
            self.visit(stmt)
        for attr in acquired:
            self.held.discard(attr)

    # nested defs run at a different time than the enclosing lock region:
    # their bodies are checked as unheld (closures dispatched later must not
    # inherit the lexical lock context)
    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        inner = _MethodChecker(self.source, self.cls_name, self.locks,
                               set(), self.findings)
        for stmt in node.body:
            inner.visit(stmt)

    visit_AsyncFunctionDef = visit_FunctionDef  # type: ignore[assignment]

    # -- guarded attribute accesses -------------------------------------

    def visit_Attribute(self, node: ast.Attribute) -> None:
        attr = _self_attr(node)
        if attr is not None and attr in self.guarded:
            lock = self.guarded[attr]
            if lock not in self.held:
                verb = ("written" if isinstance(node.ctx,
                                                (ast.Store, ast.Del))
                        else "read")
                self.findings.append(Finding(
                    "lock-guard", self.source.relpath, node.lineno,
                    f"{self.cls_name}.{attr} {verb} outside "
                    f"`with self.{lock}` (declared `# guards: {attr}`)"))
        self.generic_visit(node)

    # -- blocking calls under a held lock -------------------------------

    def visit_Call(self, node: ast.Call) -> None:
        if self.held:
            blocking = self._blocking_name(node)
            if blocking is not None:
                held = ", ".join(sorted(self.held))
                self.findings.append(Finding(
                    "lock-blocking", self.source.relpath, node.lineno,
                    f"blocking call {blocking} while holding "
                    f"self.{held} — a stall here wedges every thread "
                    "contending on the lock"))
        self.generic_visit(node)

    def _blocking_name(self, node: ast.Call) -> str | None:
        fn = node.func
        if isinstance(fn, ast.Attribute):
            # module-attr forms: time.sleep(...), socket.create_connection
            if isinstance(fn.value, ast.Name):
                mod, name = fn.value.id, fn.attr
                if (mod, name) == ("time", "sleep"):
                    return "time.sleep()"
                if mod == "socket":
                    return f"socket.{name}()"
            if fn.attr == "join" and not node.args:
                return ".join()"
            if fn.attr == "wait":
                # Condition.wait on the HELD lock releases it — correct;
                # Event.wait / anything-else.wait blocks while holding
                recv = _self_attr(fn.value)
                if recv is not None and recv in self.held:
                    return None
                return ".wait()"
            if fn.attr == "get" and _is_blocking_get(node):
                return ".get()"
            if fn.attr in _BLOCKING_ATTRS:
                return f".{fn.attr}()"
        elif isinstance(fn, ast.Name) and fn.id in _BLOCKING_BARE:
            return f"{fn.id}()"
        return None


def _is_blocking_get(node: ast.Call) -> bool:
    """True for queue-shaped blocking `.get()` forms: bare `q.get()`,
    `q.get(timeout=...)`, `q.get(True)`, `q.get(block=True)`. A first
    positional arg that is not the literal True reads as `dict.get(key)`
    (exempt), and an explicit `block=False` is non-blocking."""
    if node.args:
        first = node.args[0]
        return isinstance(first, ast.Constant) and first.value is True
    for kw in node.keywords:
        if kw.arg == "block":
            return not (isinstance(kw.value, ast.Constant)
                        and kw.value.value is False)
    return True  # bare get() / get(timeout=...): blocks


def check_locks(sources: list[Source]) -> list[Finding]:
    findings: list[Finding] = []
    for source in sources:
        if source.tree is None:
            continue
        for cls in ast.walk(source.tree):
            if not isinstance(cls, ast.ClassDef):
                continue
            locks = _collect_locks(source, cls)
            if not locks.locks:
                continue
            for meth in cls.body:
                if not isinstance(meth, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                    continue
                if meth.name in ("__init__", "__post_init__"):
                    continue  # construction happens-before publication
                held = set()
                m = marker_on(source, meth, _HOLDS_RE)
                if m:
                    held = {h for h in _names(m.group(1))
                            if h in locks.locks}
                checker = _MethodChecker(source, cls.name, locks, held,
                                         findings)
                for stmt in meth.body:
                    checker.visit(stmt)
    return findings
