"""Hot-path host-sync lint (rules `hot-sync`, `hot-impure`).

BENCH_r03/r04 documented the failure mode this pass exists for: a silent
device->host sync (or an accidental recompile) landing on the decode hot
path and reaching hardware undetected, halving throughput with no test
failing. The conventions:

    def _issue_super_step(...):  # hot-path
        A host-side hot function (scheduler issue/deliver/chain paths, the
        sampler). Must not contain IMPLICIT device->host syncs:
          - `.item()`, `.tolist()` calls
          - `np.asarray(...)` / `np.array(...)` (fetches a jax array)
          - `jax.device_get(...)`
          - `float(x[i])` / `int(x[i])` / `bool(x[i])` on subscripted values
            (the classic scalar-read sync)
          - `print(...)` (printing a tracer/array syncs and stalls)
        Names assigned FROM an `np.asarray(...)` call earlier in the same
        function are known host arrays; subsequent `.tolist()`/`int(x[i])`
        on them are exempt — only the fetch itself is the sync to triage.

    def step(carry, i):  # hot-path: traced
        A jit-traced body (device_loop scan/verify bodies). All of the
        above, plus trace-impure calls that would bake a host value into
        the compiled program or recompile per call: `time.*`, `random.*`,
        `np.random.*`, `np.asarray` on traced values, `uuid.*`,
        `os.environ` reads.

Deliberate syncs (the delivery fence in `_deliver_super_step`) carry
`# dlint: ignore[hot-sync] -- reason` — the point is that every sync on a
hot path is WRITTEN DOWN, not that none exist.
"""

from __future__ import annotations

import ast
import re

from .core import Finding, Source, marker_on

_HOT_RE = re.compile(r"#\s*hot-path(?::\s*(traced))?\b")

_SYNC_ATTRS = {"item", "tolist"}
_IMPURE_MODULES = {"time", "random", "uuid"}


def _dotted(fn: ast.AST) -> str | None:
    """'a.b.c' for nested attribute of names, else None."""
    parts = []
    while isinstance(fn, ast.Attribute):
        parts.append(fn.attr)
        fn = fn.value
    if isinstance(fn, ast.Name):
        parts.append(fn.id)
        return ".".join(reversed(parts))
    return None


class _HotChecker(ast.NodeVisitor):
    def __init__(self, source: Source, fn_name: str, traced: bool,
                 findings: list[Finding]):
        self.source = source
        self.fn_name = fn_name
        self.traced = traced
        self.findings = findings
        self.host_names: set[str] = set()  # assigned from np.asarray & co.

    def _flag(self, rule: str, node: ast.AST, msg: str) -> None:
        self.findings.append(Finding(
            rule, self.source.relpath, node.lineno,
            f"{msg} in hot-path function `{self.fn_name}`"))

    # HOST hot-path status does not flow into nested defs (a closure built
    # here may run on a different path; the author marks it explicitly) —
    # but TRACED status does: a scan/verify `step` defined inside a jitted
    # `loop` body executes at trace time, so its impurities are the loop's
    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        if not self.traced:
            return
        inner = _HotChecker(self.source, f"{self.fn_name}.{node.name}",
                            traced=True, findings=self.findings)
        for stmt in node.body:
            inner.visit(stmt)

    visit_AsyncFunctionDef = visit_FunctionDef  # type: ignore[assignment]

    @staticmethod
    def _is_fetch(node: ast.AST) -> bool:
        return (isinstance(node, ast.Call)
                and _dotted(node.func) in ("np.asarray", "np.array",
                                           "numpy.asarray", "numpy.array",
                                           "jax.device_get"))

    def visit_Assign(self, node: ast.Assign) -> None:
        # np.asarray(...) result names are HOST arrays from here on — also
        # through a conditional fetch (`x = np.asarray(a) if cond else None`)
        val = node.value
        fetched = (self._is_fetch(val)
                   or (isinstance(val, ast.IfExp)
                       and (self._is_fetch(val.body)
                            or self._is_fetch(val.orelse))))
        if fetched:
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    self.host_names.add(tgt.id)
        self.generic_visit(node)

    def _roots_host(self, node: ast.AST) -> bool:
        """True when the expression's root name is a known host array."""
        while isinstance(node, (ast.Subscript, ast.Attribute)):
            node = node.value
        return isinstance(node, ast.Name) and node.id in self.host_names

    def visit_Call(self, node: ast.Call) -> None:
        fn = node.func
        dotted = _dotted(fn)
        # -- implicit device->host syncs --------------------------------
        if isinstance(fn, ast.Attribute) and fn.attr in _SYNC_ATTRS:
            if not self._roots_host(fn.value):
                self._flag("hot-sync", node,
                           f"`.{fn.attr}()` forces a device->host sync")
        elif dotted in ("np.asarray", "np.array", "numpy.asarray",
                        "numpy.array"):
            self._flag("hot-sync", node,
                       f"`{dotted}(...)` blocks on a device->host transfer "
                       "when given a device array")
        elif dotted == "jax.device_get":
            self._flag("hot-sync", node, "`jax.device_get(...)` is an "
                       "explicit device->host sync")
        elif (isinstance(fn, ast.Name) and fn.id in ("float", "int", "bool")
              and node.args and isinstance(node.args[0], ast.Subscript)
              and not self._roots_host(node.args[0])):
            self._flag("hot-sync", node,
                       f"`{fn.id}(x[...])` reads one element to host "
                       "(a per-call sync)")
        elif isinstance(fn, ast.Name) and fn.id == "print":
            self._flag("hot-sync", node,
                       "`print(...)` on a hot path (stalls; printing an "
                       "array or tracer also syncs)")
        # -- trace-impure calls inside jitted bodies ---------------------
        if self.traced and dotted is not None:
            root = dotted.split(".", 1)[0]
            if root in _IMPURE_MODULES or dotted.startswith("np.random."):
                self._flag("hot-impure", node,
                           f"`{dotted}(...)` is trace-impure: its value is "
                           "baked in at compile time (or recompiles per "
                           "call) inside a jitted body")
        self.generic_visit(node)

    def visit_Subscript(self, node: ast.Subscript) -> None:
        if self.traced and _dotted(node.value) == "os.environ":
            self._flag("hot-impure", node,
                       "`os.environ[...]` read inside a jitted body is "
                       "baked in at compile time")
        self.generic_visit(node)


def check_hot_paths(sources: list[Source]) -> list[Finding]:
    findings: list[Finding] = []
    for source in sources:
        if source.tree is None:
            continue
        for node in ast.walk(source.tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            m = marker_on(source, node, _HOT_RE)
            if m is None:
                continue
            checker = _HotChecker(source, node.name,
                                  traced=m.group(1) == "traced",
                                  findings=findings)
            for stmt in node.body:
                checker.visit(stmt)
    return findings
