"""Shared infrastructure for the repo-native static-analysis passes.

Every pass (analysis/locks.py, hotpath.py, drift.py, smoke.py) consumes the
same parsed `Source` objects and emits the same `Finding` records; the
runner (analysis/runner.py, CLI perf/dlint.py) applies the one suppression
convention to all of them:

    # dlint: ignore[rule] -- reason
    # dlint: ignore[rule-a,rule-b] -- reason covering both

A suppression silences findings of the named rule(s) on ITS line only — a
file- or block-wide mute does not exist by design: each finding is triaged
individually, and the written reason (mandatory; a reasonless suppression is
itself a `bad-suppression` finding) survives next to the code it excuses.
`ignore[*]` matches any rule; use it only for lines tripping several rules
for one underlying cause. Suppressions are counted and reported (JSON +
text) so a silently-growing pile of excuses is visible in review.

Annotation conventions parsed here (consumed by locks.py / hotpath.py):

    self._lock = threading.Lock()  # guards: _pending, _thread
        declares which attributes of the owning class the lock protects
    def _deliver(...):  # holds: self._lock
        declares a method that is only ever called with the lock held
    def _emit(...):  # hot-path
        marks a host-side hot function: no implicit device->host syncs
    def step(...):  # hot-path: traced
        marks a jit-traced body: additionally no trace-impure calls

All comment parsing is line-anchored on the physical source line of the
relevant AST node, so the conventions work without any tokenizer pass.
"""

from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass, field

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

# first-party scan roots, mirroring the original perf/smoke_lint.py scope
SCAN_DIRS = ("distributed_llama_tpu", "tests", "perf", "examples")
TOP_FILES = ("bench.py", "launch.py", "__graft_entry__.py")

_SUPPRESS_RE = re.compile(
    r"#\s*dlint:\s*ignore\[([^\]]*)\](\s*--\s*(.*\S))?")


@dataclass
class Finding:
    """One triaged-or-triagable defect report."""

    rule: str
    path: str       # repo-relative
    line: int       # 1-based; 0 = file-level
    message: str
    suppressed: bool = False
    reason: str = ""  # the suppression's written reason, when suppressed

    def format(self) -> str:
        loc = f"{self.path}:{self.line}" if self.line else self.path
        tag = f" (suppressed: {self.reason})" if self.suppressed else ""
        return f"{loc}: [{self.rule}] {self.message}{tag}"

    def as_dict(self) -> dict:
        d = {"rule": self.rule, "path": self.path, "line": self.line,
             "message": self.message, "suppressed": self.suppressed}
        if self.suppressed:
            d["reason"] = self.reason
        return d


@dataclass
class Suppression:
    path: str
    line: int
    rules: tuple[str, ...]
    reason: str
    used: int = 0


@dataclass
class Source:
    """One parsed first-party file. `tree` is None on a syntax error (the
    compile pass reports that; AST passes skip the file)."""

    path: str          # absolute
    relpath: str
    text: str
    lines: list[str] = field(default_factory=list)
    tree: ast.AST | None = None
    suppressions: dict[int, Suppression] = field(default_factory=dict)

    def line_text(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1]
        return ""


def repo_py_files(repo: str = REPO) -> list[str]:
    """Every first-party .py (same roots the original smoke lint scanned)."""
    out = []
    for d in SCAN_DIRS:
        for root, dirs, files in os.walk(os.path.join(repo, d)):
            dirs[:] = [x for x in dirs
                       if not x.startswith((".", "__pycache__"))]
            out.extend(os.path.join(root, f) for f in files
                       if f.endswith(".py"))
    out.extend(os.path.join(repo, f) for f in TOP_FILES
               if os.path.exists(os.path.join(repo, f)))
    return sorted(out)


def package_py_files(repo: str = REPO) -> list[str]:
    """The `distributed_llama_tpu` package only — the scope of the
    annotation-driven passes (tests/perf deliberately violate rules in
    fixtures and bench scratch code)."""
    pkg = "distributed_llama_tpu" + os.sep
    return [f for f in repo_py_files(repo)
            if os.path.relpath(f, repo).startswith(pkg)]


def _real_comments(text: str) -> list[tuple[int, str]] | None:
    """[(line, comment)] via the tokenizer, so a docstring QUOTING the
    suppression syntax is never mistaken for one; None when the file does
    not tokenize (the compile pass reports it, callers fall back to the
    line scan)."""
    import io
    import tokenize

    out = []
    try:
        for tok in tokenize.generate_tokens(io.StringIO(text).readline):
            if tok.type == tokenize.COMMENT:
                out.append((tok.start[0], tok.string))
    except (tokenize.TokenError, SyntaxError, IndentationError):
        return None
    return out


def parse_suppressions(path: str, relpath: str, lines: list[str],
                       text: str | None = None
                       ) -> tuple[dict[int, Suppression], list[Finding]]:
    """Collect `# dlint: ignore[...] -- reason` markers (real comments only).
    A marker without a written reason is a finding, not a suppression — the
    whole point of the convention is that every excuse is recorded."""
    sups: dict[int, Suppression] = {}
    findings: list[Finding] = []
    comments = _real_comments(text if text is not None
                              else "\n".join(lines))
    if comments is None:  # untokenizable: conservative line scan
        comments = list(enumerate(lines, start=1))
    for i, line in comments:
        m = _SUPPRESS_RE.search(line)
        if not m:
            continue
        rules = tuple(r.strip() for r in m.group(1).split(",") if r.strip())
        reason = (m.group(3) or "").strip()
        if not rules or not reason:
            findings.append(Finding(
                "bad-suppression", relpath, i,
                "suppression needs `# dlint: ignore[rule] -- reason` with a "
                "non-empty rule list AND a written reason"))
            continue
        sups[i] = Suppression(relpath, i, rules, reason)
    return sups, findings


def load_source(path: str, repo: str = REPO) -> Source:
    with open(path, encoding="utf-8") as fh:
        text = fh.read()
    relpath = os.path.relpath(path, repo)
    lines = text.splitlines()
    try:
        tree = ast.parse(text)
    except SyntaxError:
        tree = None  # the compile pass reports this file
    sups, bad = parse_suppressions(path, relpath, lines, text)
    src = Source(path, relpath, text, lines, tree, sups)
    # bad-suppression findings ride on the source so the runner collects
    # them exactly once per file
    src.bad_suppressions = bad  # type: ignore[attr-defined]
    return src


def load_sources(files: list[str] | None = None,
                 repo: str = REPO) -> list[Source]:
    return [load_source(f, repo) for f in (files if files is not None
                                           else repo_py_files(repo))]


def apply_suppressions(sources: list[Source],
                       findings: list[Finding]) -> list[Finding]:
    """Mark findings whose line carries a matching suppression. Returns the
    same list (mutated) for chaining; Suppression.used counts consumers."""
    by_rel = {s.relpath: s for s in sources}
    for f in findings:
        src = by_rel.get(f.path)
        if src is None:
            continue
        sup = src.suppressions.get(f.line)
        if sup is None:
            continue
        if "*" in sup.rules or f.rule in sup.rules:
            f.suppressed = True
            f.reason = sup.reason
            sup.used += 1
    return findings


def comment_on(source: Source, lineno: int) -> str:
    """The comment tail of a physical line ('' when none)."""
    line = source.line_text(lineno)
    i = line.find("#")
    return line[i:] if i != -1 else ""


def marker_on(source: Source, node: ast.AST, pattern: re.Pattern,
              look_above: int = 2) -> re.Match | None:
    """Search `pattern` in the comment of the node's def/decl line, or in up
    to `look_above` immediately preceding COMMENT-ONLY lines (the decorator /
    leading-comment zone) — a trailing comment on unrelated preceding code
    never marks the node below it."""
    start = getattr(node, "lineno", 0)
    m = pattern.search(comment_on(source, start))
    if m:
        return m
    for ln in range(start - 1, max(start - look_above - 1, 0), -1):
        text = source.line_text(ln).strip()
        if not text.startswith("#"):
            break
        m = pattern.search(text)
        if m:
            return m
    return None
