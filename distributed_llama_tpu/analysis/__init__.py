"""Repo-native static analysis (docs/ANALYSIS.md).

Dependency-free AST passes over the repo's own concurrency and hot-path
conventions, plus a runtime compile-manifest auditor, unified behind one
runner (perf/dlint.py, tier-1 via tests/test_dlint.py):

    from distributed_llama_tpu.analysis import runner
    report = runner.run()            # static passes
    report = runner.run(compile_gate=True)   # + tiny-model compile audit

The package imports NOTHING heavy at module scope — the static passes are
pure stdlib (ast/compileall/re), so dlint runs in any environment the repo
checks out in; only the compile-manifest gate touches jax, and only when
asked.
"""

from . import core  # noqa: F401  (re-export surface: Finding et al.)
from .core import Finding, Source, repo_py_files  # noqa: F401

__all__ = ["core", "Finding", "Source", "repo_py_files"]
