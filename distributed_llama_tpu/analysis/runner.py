"""Unified static-analysis runner (CLI: perf/dlint.py, tier-1:
tests/test_dlint.py).

Composes every pass over one shared parse of the repo:

  compile / dead-import      repo-wide      (analysis/smoke.py, migrated)
  lock-guard / lock-blocking package-wide   (analysis/locks.py)
  hot-sync / hot-impure      package-wide   (analysis/hotpath.py)
  metric-docs / fault-docs   package-wide   (analysis/drift.py)
  bad-suppression            repo-wide      (analysis/core.py)

plus, opted in separately because it executes the tiny-model engine
(`compile_gate=True` / `perf/dlint.py --compile-gate`):

  compile-manifest           runtime        (analysis/compile_audit.py)

The report separates unsuppressed findings (gate tier-1 at zero) from
suppressed ones (each carrying its written reason) and lists stale
suppressions that matched nothing — an excuse that outlived its defect
should be deleted, not trusted.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field

from . import drift, hotpath, locks, smoke
from .core import (REPO, Finding, Source, apply_suppressions, load_sources,
                   repo_py_files)


@dataclass
class Report:
    findings: list[Finding] = field(default_factory=list)
    files_scanned: int = 0
    unused_suppressions: list[dict] = field(default_factory=list)

    @property
    def unsuppressed(self) -> list[Finding]:
        return [f for f in self.findings if not f.suppressed]

    @property
    def suppressed(self) -> list[Finding]:
        return [f for f in self.findings if f.suppressed]

    def counts_by_rule(self) -> dict[str, int]:
        out: dict[str, int] = {}
        for f in self.unsuppressed:
            out[f.rule] = out.get(f.rule, 0) + 1
        return out

    def as_dict(self) -> dict:
        return {
            "files_scanned": self.files_scanned,
            "findings": [f.as_dict() for f in self.unsuppressed],
            "suppressions": [f.as_dict() for f in self.suppressed],
            "unused_suppressions": self.unused_suppressions,
            "counts": {
                "unsuppressed": len(self.unsuppressed),
                "suppressed": len(self.suppressed),
                "by_rule": self.counts_by_rule(),
            },
            "ok": not self.unsuppressed,
        }

    def to_json(self) -> str:
        return json.dumps(self.as_dict(), indent=2, sort_keys=True)

    def format_text(self) -> str:
        lines = [f.format() for f in self.unsuppressed]
        lines.append(
            f"dlint: {self.files_scanned} files, "
            f"{len(self.unsuppressed)} finding(s), "
            f"{len(self.suppressed)} suppressed, "
            f"{len(self.unused_suppressions)} stale suppression(s)")
        return "\n".join(lines)


def run(files: list[str] | None = None, repo: str = REPO,
        compile_gate: bool = False, manifest_path: str | None = None
        ) -> Report:
    """Run every static pass (and optionally the runtime compile-manifest
    gate) and return the triaged report."""
    paths = files if files is not None else repo_py_files(repo)
    sources = load_sources(paths, repo)
    findings: list[Finding] = []
    findings.extend(smoke.check_compile(paths, repo))
    findings.extend(smoke.check_dead_imports(sources, repo))
    findings.extend(locks.check_locks(sources))
    findings.extend(hotpath.check_hot_paths(sources))
    findings.extend(drift.check_metric_docs(sources))
    findings.extend(drift.check_fault_docs(sources))
    for s in sources:
        findings.extend(getattr(s, "bad_suppressions", ()))
    if compile_gate:
        from . import compile_audit

        findings.extend(compile_audit.check_manifest(manifest_path))
    apply_suppressions(sources, findings)
    report = Report(findings=findings, files_scanned=len(sources))
    for s in sources:
        for sup in s.suppressions.values():
            if sup.used == 0:
                report.unused_suppressions.append(
                    {"path": sup.path, "line": sup.line,
                     "rules": list(sup.rules), "reason": sup.reason})
    return report


__all__ = ["Report", "run", "Source", "Finding"]
