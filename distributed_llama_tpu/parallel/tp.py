"""Tensor-parallel execution: shard params onto the mesh and build the SPMD step.

This layer replaces the reference's entire distribution machinery — weight streaming to
workers (transformer.cpp:432-451), per-layer broadcast/gather sync tasks (tasks.cpp:44-94),
and the root/worker role split (tasks.hpp:52-76). One shard_map'd program runs on every
device; `jax.device_put` with NamedShardings performs the "weight distribution"; XLA
lowers the psum/all_gather merge points to ICI/DCN collectives.
"""

from __future__ import annotations

import functools
from typing import Any

import jax
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

from ..models.forward import forward
from ..models.spec import ModelSpec
from ..ops.rope import RopeTables
from ..quants import QTensor
from .mesh import AXIS_SP, AXIS_TP
from .sharding import check_divisibility, kv_cache_pspec_for_mesh, param_pspecs


def _expand_pspec_tree(params: dict[str, Any], pspecs: dict[str, Any]):
    """Expand a per-tensor spec dict into a pytree congruent with params (QTensor nodes
    get their single spec broadcast to data+scales leaves, which line up by axis index)."""
    out = {}
    for k, v in params.items():
        if isinstance(v, dict):
            out[k] = _expand_pspec_tree(v, pspecs[k])
        elif isinstance(v, QTensor):
            spec = pspecs[k]
            out[k] = QTensor(v.ftype, spec, spec if v.scales is not None else None,
                             layout=v.layout)
        else:
            out[k] = pspecs[k]
    return out


def shard_params(params: dict[str, Any], mesh: Mesh,
                 spec: ModelSpec | None = None) -> dict[str, Any]:
    """Place params on the mesh per param_pspecs — the TPU-native 'loadRoot' weight
    distribution (transformer.cpp:480-539) with device_put instead of socket writes."""
    if spec is not None:
        check_divisibility(spec, mesh.shape[AXIS_TP])
    pspec_tree = _expand_pspec_tree(params, param_pspecs(params))

    def put(leaf, spec):
        return jax.device_put(leaf, NamedSharding(mesh, spec))

    return jax.tree_util.tree_map(put, params, pspec_tree,
                                  is_leaf=lambda x: isinstance(x, P))


def make_sharded_forward(spec: ModelSpec, mesh: Mesh, params: dict[str, Any], *,
                         dtype=None, use_pallas: bool = False,
                         compress_collectives: bool = False, donate_cache: bool = True):
    """Build the jitted SPMD forward step over the mesh's tp axis.

    Returns fn(params, rope, tokens, k_cache, v_cache, start_pos) ->
    (logits, k_cache, v_cache). Cache buffers are donated (in-place update in HBM).
    """
    import jax.numpy as jnp

    tp = mesh.shape[AXIS_TP]
    sp = mesh.shape.get(AXIS_SP, 1)
    check_divisibility(spec, tp, sp)
    dtype = dtype or jnp.float32

    param_specs = _expand_pspec_tree(params, param_pspecs(params))
    kv_spec = kv_cache_pspec_for_mesh(mesh)

    fwd = functools.partial(forward, spec=spec, dtype=dtype, axis_name=AXIS_TP,
                            sp_axis_name=AXIS_SP if sp > 1 else None, sp_size=sp,
                            use_pallas=use_pallas,
                            compress_collectives=compress_collectives)
    rope_type = spec.rope_type

    def step(p, rope_cos, rope_sin, tokens, kc, vc, start_pos):
        rope = RopeTables(rope_cos, rope_sin, rope_type)
        return fwd(p, rope=rope, tokens=tokens, k_cache=kc, v_cache=vc,
                   start_pos=start_pos)

    sharded = jax.shard_map(
        step, mesh=mesh,
        in_specs=(param_specs, P(), P(), P(), kv_spec, kv_spec, P()),
        out_specs=(P(), kv_spec, kv_spec),
        check_vma=False,
    )
    donate = (4, 5) if donate_cache else ()
    jitted = jax.jit(sharded, donate_argnums=donate)

    def run(p, rope: RopeTables, tokens, kc, vc, start_pos):
        return jitted(p, rope.cos, rope.sin, tokens, kc, vc, start_pos)

    return run
