"""Tensor-parallel execution: shard params onto the mesh and build the SPMD step.

This layer replaces the reference's entire distribution machinery — weight streaming to
workers (transformer.cpp:432-451), per-layer broadcast/gather sync tasks (tasks.cpp:44-94),
and the root/worker role split (tasks.hpp:52-76). One shard_map'd program runs on every
device; `jax.device_put` with NamedShardings performs the "weight distribution"; XLA
lowers the psum/all_gather merge points to ICI/DCN collectives.
"""

from __future__ import annotations

import functools
from typing import Any

import jax
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

from ..models.forward import forward
from ..models.spec import ModelSpec
from ..ops.rope import RopeTables
from ..quants import QTensor
from .mesh import AXIS_SP, AXIS_TP
from .sharding import (check_divisibility, effective_kv_heads, kv_cache_pspec_for_mesh,
                       param_pspecs)


def _expand_pspec_tree(params: dict[str, Any], pspecs: dict[str, Any]):
    """Expand a per-tensor spec dict into a pytree congruent with params (QTensor nodes
    get their single spec broadcast to data+scales leaves, which line up by axis index)."""
    out = {}
    for k, v in params.items():
        if isinstance(v, dict):
            out[k] = _expand_pspec_tree(v, pspecs[k])
        elif isinstance(v, QTensor):
            spec = pspecs[k]
            out[k] = QTensor(v.ftype, spec, spec if v.scales is not None else None,
                             layout=v.layout, groups=v.groups,
                             row_groups=v.row_groups)
        else:
            out[k] = pspecs[k]
    return out


def _repeat_kv_rows(t: QTensor | Any, hk: int, rep: int) -> Any:
    """Replicate each KV head's row block `rep` times along the row (out) axis.

    Leaves are stacked (L, hk*hs, ...) arrays; rows stay whole-head-grouped so
    P('tp') on the row axis lands KV head j*hk//tp on shard j — exactly the head
    shard j's query slice attends with. Quant blocks run along the *in* axis, so
    row replication never splits a block.
    """
    import numpy as np

    def rep_leaf(a):
        if a is None:
            return None
        rows = a.shape[1]
        assert rows % hk == 0, (a.shape, hk)
        hs_g = rows // hk
        xp = np if isinstance(a, np.ndarray) else jax.numpy
        grouped = a.reshape(a.shape[0], hk, hs_g, *a.shape[2:])
        out = xp.repeat(grouped, rep, axis=1)
        return out.reshape(a.shape[0], hk * rep * hs_g, *a.shape[2:])

    if isinstance(t, QTensor):
        return QTensor(t.ftype, rep_leaf(t.data), rep_leaf(t.scales), layout=t.layout)
    return rep_leaf(t)


def shard_params(params: dict[str, Any], mesh: Mesh,
                 spec: ModelSpec | None = None,
                 moe_sharding: str = "slice") -> dict[str, Any]:
    """Place params on the mesh per param_pspecs — the TPU-native 'loadRoot' weight
    distribution (transformer.cpp:480-539) with device_put instead of socket writes.

    When tp > n_kv_heads, wk/wv rows are replicated per KV head (effective_kv_heads)
    before placement, lifting the reference's nSlices <= nKvHeads limit."""
    tp = mesh.shape[AXIS_TP]
    # fused matvec groups carry the TP-group count their rows were interleaved
    # with (models/params.py fuse_matvec_groups); placement on a mismatched
    # mesh/moe_sharding would silently scramble the member split — fail loudly
    from ..models.params import _FUSE_GROUPS

    for name, t in params["blocks"].items():
        if name not in _FUSE_GROUPS or not isinstance(t, QTensor):
            continue
        expected = 1 if (name == "moe_gu" and moe_sharding == "expert") else tp
        assert t.row_groups == expected, (
            f"{name} was fused with row interleave {t.row_groups}, but this "
            f"mesh shards it over {expected} group(s) (tp={tp}, "
            f"moe_sharding={moe_sharding}) — re-run prepare_for_pallas with "
            "the deployment's tp/moe_sharding")
    if spec is not None:
        check_divisibility(spec, tp, moe_sharding=moe_sharding)
        hk_eff = effective_kv_heads(spec, tp)
        if hk_eff != spec.n_kv_heads:
            rep = hk_eff // spec.n_kv_heads
            params = dict(params, blocks=dict(params["blocks"]))
            for name in ("wk", "wv"):
                params["blocks"][name] = _repeat_kv_rows(
                    params["blocks"][name], spec.n_kv_heads, rep)
    pspec_tree = _expand_pspec_tree(params, param_pspecs(params, moe_sharding))

    def put(leaf, spec):
        return jax.device_put(leaf, NamedSharding(mesh, spec))

    return jax.tree_util.tree_map(put, params, pspec_tree,
                                  is_leaf=lambda x: isinstance(x, P))


def init_sharded_kv_cache(spec: ModelSpec, mesh: Mesh, batch: int = 1, dtype=None):
    """Zeroed KV caches with the head axis already expanded for KV-head replication
    and placed with the mesh's cache sharding. The one cache-construction path for
    every sharded entry point — callers can't forget effective_kv_heads."""
    import jax.numpy as jnp

    from ..models.forward import init_kv_cache

    dtype = dtype or jnp.float32
    hk = effective_kv_heads(spec, mesh.shape[AXIS_TP])
    kc, vc = init_kv_cache(spec, batch=batch, dtype=dtype, n_kv_heads=hk)
    sh = NamedSharding(mesh, kv_cache_pspec_for_mesh(mesh))
    return jax.device_put(kc, sh), jax.device_put(vc, sh)


def make_sharded_forward(spec: ModelSpec, mesh: Mesh, params: dict[str, Any], *,
                         dtype=None, use_pallas: bool = False,
                         compress_collectives: bool = False, donate_cache: bool = True,
                         attn_window: int | None = None,
                         cache_write: str = "inscan",
                         moe_sharding: str = "slice",
                         fused_prologue: bool = False,
                         kv_block_tokens: int = 0,
                         paged_kernel: bool = False):
    """Build the jitted SPMD forward step over the mesh's tp axis.

    Returns fn(params, rope, tokens, k_cache, v_cache, start_pos) ->
    (logits, k_cache, v_cache). Cache buffers are donated (in-place update in HBM).
    attn_window statically bounds the cache positions attention reads (see
    models.forward.forward); callers must keep start_pos + T <= attn_window.

    kv_block_tokens > 0 selects the device-resident paged KV layout
    (docs/PAGED_KV.md): the caches are a (L, N, hk, bt, hs) block pool and
    the returned fn takes a trailing per-row block-table argument —
    fn(params, rope, tokens, k_cache, v_cache, start_pos, tables).
    """
    import jax.numpy as jnp

    from .mesh import AXIS_DP

    tp = mesh.shape[AXIS_TP]
    sp = mesh.shape.get(AXIS_SP, 1)
    dp = mesh.shape.get(AXIS_DP, 1)
    check_divisibility(spec, tp, sp, moe_sharding=moe_sharding)
    dtype = dtype or jnp.float32
    if sp > 1 and cache_write != "deferred":
        # the in-scan (contiguous) ring walks the full sharded cache; the
        # deferred ring is STRIPED and honors the window (models/forward.py)
        attn_window = None

    param_specs = _expand_pspec_tree(params, param_pspecs(params, moe_sharding))
    kv_spec = kv_cache_pspec_for_mesh(mesh)
    # data parallelism: batch rows shard over dp (cache rows already carry AXIS_DP on
    # their batch axis); each dp group runs an independent replica of the tp/sp
    # program with zero cross-group traffic — the throughput axis the reference
    # lacks entirely (batch hard-wired to 1, funcs.cpp:424). start_pos must then be
    # per-row (B,), sharded alongside the rows.
    tok_spec = P(AXIS_DP) if dp > 1 else P()
    pos_spec = P(AXIS_DP) if dp > 1 else P()

    paged = kv_block_tokens > 0
    if paged:
        assert sp == 1 and dp == 1, "paged KV is tp-only (no sp/dp sharding)"
        # pool layout (L, N, hk, bt, hs): heads stay on tp, blocks replicated
        kv_spec = P(None, None, AXIS_TP)
    # a 1-member tp axis has nothing to reduce: drop the axis name so every
    # psum/all_gather elides AND the "fused" policy may fold residual adds
    # into the matmul kernels (illegal before a real TP merge). Compressed
    # collectives keep the axis — the Q80 wire quantization is part of their
    # numerics even over one member.
    tp_axis = AXIS_TP if (tp > 1 or compress_collectives) else None
    fwd = functools.partial(forward, spec=spec, dtype=dtype, axis_name=tp_axis,
                            sp_axis_name=AXIS_SP if sp > 1 else None, sp_size=sp,
                            use_pallas=use_pallas,
                            compress_collectives=compress_collectives,
                            attn_window=attn_window, cache_write=cache_write,
                            fused_prologue=fused_prologue,
                            block_tokens=kv_block_tokens,
                            paged_kernel=paged_kernel)
    rope_type = spec.rope_type

    from ..compat import shard_map

    if paged:
        def step(p, rope_cos, rope_sin, tokens, kc, vc, start_pos, tables):
            rope = RopeTables(rope_cos, rope_sin, rope_type)
            return fwd(p, rope=rope, tokens=tokens, k_cache=kc, v_cache=vc,
                       start_pos=start_pos, block_tables=tables)

        sharded = shard_map(
            step, mesh=mesh,
            in_specs=(param_specs, P(), P(), tok_spec, kv_spec, kv_spec,
                      pos_spec, P()),
            out_specs=(tok_spec, kv_spec, kv_spec),
            check_vma=False,
        )
        donate = (4, 5) if donate_cache else ()
        jitted = jax.jit(sharded, donate_argnums=donate)

        def run(p, rope: RopeTables, tokens, kc, vc, start_pos, tables):
            return jitted(p, rope.cos, rope.sin, tokens, kc, vc, start_pos,
                          tables)

        return run

    def step(p, rope_cos, rope_sin, tokens, kc, vc, start_pos):
        rope = RopeTables(rope_cos, rope_sin, rope_type)
        return fwd(p, rope=rope, tokens=tokens, k_cache=kc, v_cache=vc,
                   start_pos=start_pos)

    sharded = shard_map(
        step, mesh=mesh,
        in_specs=(param_specs, P(), P(), tok_spec, kv_spec, kv_spec, pos_spec),
        out_specs=(tok_spec, kv_spec, kv_spec),
        check_vma=False,
    )
    donate = (4, 5) if donate_cache else ()
    jitted = jax.jit(sharded, donate_argnums=donate)

    def run(p, rope: RopeTables, tokens, kc, vc, start_pos):
        return jitted(p, rope.cos, rope.sin, tokens, kc, vc, start_pos)

    return run
