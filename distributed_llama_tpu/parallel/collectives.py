"""Collectives, including int8-compressed all-reduce.

The reference compresses every inter-node activation transfer to Q80 (F32->int8+f16
scale) before the TCP write and dequantizes after (src/tasks.cpp:96-135), cutting wire
bytes ~3.8x (README.md:135-147). On TPU the analog is quantizing the *collective*
payload.

`quantized_psum` is the EQuARX-style two-phase compressed all-reduce:

1. **scatter-reduce** — quantize the local partial, `all_to_all` the quantized
   shards so device d holds every peer's copy of shard d ((n-1)/n of the
   compressed payload on the wire), dequantize and sum locally;
2. **gather** — re-quantize the reduced shard and `all_gather` it back to the
   full vector ((n-1)/n of the compressed payload again).

Total per-device wire bytes: 2*(n-1)/n x (34/32 bytes/elem) — the same ring
all-reduce factor as the fp path at ~3.8x less payload, and exactly what
`runtime/engine.py collective_kbytes_per_token(compress=True)` models (the
estimate is pinned against the measured jaxpr accounting in
tests/test_engine.py). The earlier single-phase form — all_gather the FULL
quantized payload and sum locally — shipped n_dev/2 x more bytes than the
model claimed; it survives as `quantized_psum_gather`, used only when the
Q80 block count doesn't divide the axis size.

On ICI this is usually a wash (bf16 psum is fast); across DCN-connected slices the
2-4x payload cut matters — same tradeoff the EQuARX paper makes inside XLA. Off by
default; measured, not assumed (SURVEY.md §7).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..quants import QK, jnp_dequantize_q80, jnp_quantize_q80


def quantized_psum_gather(x: jax.Array, axis_name: str) -> jax.Array:
    """All-reduce with Q80 payload, single-phase: all_gather the full
    quantized tensor and sum locally. Wire bytes (n-1)/n x n x payload —
    n/2 x the two-phase form — kept as the fallback for shapes whose block
    count doesn't split across the axis. x: (..., n), n % 32 == 0."""
    orig_dtype = x.dtype
    vals, scales = jnp_quantize_q80(x)
    vals_g = jax.lax.all_gather(vals, axis_name)      # (n_dev, ..., nb, 32) int8
    scales_g = jax.lax.all_gather(scales, axis_name)  # (n_dev, ..., nb) f16
    deq = jnp_dequantize_q80(vals_g, scales_g, dtype=jnp.float32)
    return jnp.sum(deq, axis=0).reshape(x.shape).astype(orig_dtype)


def quantized_psum(x: jax.Array, axis_name: str) -> jax.Array:
    """Two-phase Q80-compressed all-reduce (module docstring). x: (..., n),
    n % 32 == 0. Numerics: two quantization rounds (partials, then the
    reduced shard) instead of one — still well within the wire-compression
    error budget (tests/test_tp.py::test_compressed_collectives)."""
    n_dev = jax.lax.psum(1, axis_name)  # static: the axis size
    if n_dev <= 1:
        return x
    orig_dtype = x.dtype
    orig_shape = x.shape
    vals, scales = jnp_quantize_q80(x)  # (..., nb, 32) int8, (..., nb) f16
    nb = vals.shape[-2]
    if nb % n_dev != 0:
        return quantized_psum_gather(x, axis_name)
    # phase 1: scatter-reduce. Split the block axis into n_dev chunks;
    # all_to_all leaves device d holding every source's chunk d (the
    # inserted axis indexes the source device), dequantize + sum = this
    # device's shard of the reduced result.
    vals = vals.reshape(*vals.shape[:-2], n_dev, nb // n_dev, QK)
    scales = scales.reshape(*scales.shape[:-1], n_dev, nb // n_dev)
    vax, sax = vals.ndim - 3, scales.ndim - 2  # the n_dev chunk axes
    vals_t = jax.lax.all_to_all(vals, axis_name, split_axis=vax,
                                concat_axis=vax)
    scales_t = jax.lax.all_to_all(scales, axis_name, split_axis=sax,
                                  concat_axis=sax)
    # dequant collapses (chunk_blocks, 32) -> chunk elems; source axis at -2
    shard = jnp.sum(jnp_dequantize_q80(vals_t, scales_t, dtype=jnp.float32),
                    axis=-2)
    # phase 2: gather. Re-quantize the reduced shard and reassemble the full
    # vector; chunk index == device index, so tiled concatenation in device
    # order restores block order.
    rvals, rscales = jnp_quantize_q80(shard)  # (..., nb/n, 32), (..., nb/n)
    vals_g = jax.lax.all_gather(rvals, axis_name, axis=rvals.ndim - 2,
                                tiled=True)
    scales_g = jax.lax.all_gather(rscales, axis_name,
                                  axis=rscales.ndim - 1, tiled=True)
    out = jnp_dequantize_q80(vals_g, scales_g, dtype=jnp.float32)
    return out.reshape(orig_shape).astype(orig_dtype)


def psum(x: jax.Array, axis_name: str, compress: bool = False) -> jax.Array:
    if compress:
        return quantized_psum(x, axis_name)
    return jax.lax.psum(x, axis_name)
