"""Collectives, including int8-compressed all-reduce.

The reference compresses every inter-node activation transfer to Q80 (F32->int8+f16
scale) before the TCP write and dequantizes after (src/tasks.cpp:96-135), cutting wire
bytes ~3.8x (README.md:135-147). On TPU the analog is quantizing the *collective* payload:
`quantized_psum` sends int8 values + f16 scales through an all_gather and sums locally.

On ICI this is usually a wash (bf16 psum is fast); across DCN-connected slices the 2-4x
payload cut matters — same tradeoff the EQuARX paper makes inside XLA. Off by default;
measured, not assumed (SURVEY.md §7).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..quants import jnp_dequantize_q80, jnp_quantize_q80


def quantized_psum(x: jax.Array, axis_name: str) -> jax.Array:
    """All-reduce with Q80-compressed payload. x: (..., n), n % 32 == 0."""
    orig_dtype = x.dtype
    vals, scales = jnp_quantize_q80(x)
    vals_g = jax.lax.all_gather(vals, axis_name)      # (n_dev, ..., nb, 32) int8
    scales_g = jax.lax.all_gather(scales, axis_name)  # (n_dev, ..., nb) f16
    deq = jnp_dequantize_q80(vals_g, scales_g, dtype=jnp.float32)
    return jnp.sum(deq, axis=0).reshape(x.shape).astype(orig_dtype)


def psum(x: jax.Array, axis_name: str, compress: bool = False) -> jax.Array:
    if compress:
        return quantized_psum(x, axis_name)
    return jax.lax.psum(x, axis_name)
