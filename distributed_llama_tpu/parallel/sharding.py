"""Partition specs — the TPU equivalent of the reference's slicing layer.

Maps one-to-one onto src/commands.cpp:
    RowMatmulSlice  (split output dim d; commands.cpp:11-43)  -> 'tp' on the out axis
    ColMatmulSlice  (split input dim n; commands.cpp:45-73)   -> 'tp' on the in axis
                                                                  (+ psum in forward)
    KvCacheSlice    (kvDim/nSlices per node; commands.cpp:97-102) -> 'tp' on the kv-head
                                                                      axis of the cache
    MultiHeadAttSlice (nHeads/nSlices; commands.cpp:104-108)  -> implied by row-split QKV
    RopeSlice       (commands.cpp:75-95)                      -> nothing: rope rotates
                                                                  within a head, slicing
                                                                  is by whole heads

Because quantization blocks run along the `in` axis and a QTensor's packed/scales arrays
keep `out` and `in`(-block) at the same axis indices, ONE PartitionSpec per tensor works
as a pytree prefix for both leaves, and every slice boundary lands on a 32-block boundary
by construction (the reference asserts this dynamically, commands.cpp:16-19).
"""

from __future__ import annotations

from typing import Any

from jax.sharding import PartitionSpec as P

from ..models.spec import ModelSpec
from .mesh import AXIS_TP

# per-layer matmul tensors: axis index (within the stacked (L, ...) logical shape) that
# 'tp' shards. out-splits mirror RowMatmulSlice, in-splits mirror ColMatmulSlice.
_BLOCK_SPECS = {
    "wq": P(None, AXIS_TP),          # (L, dim->tp, dim)
    "wk": P(None, AXIS_TP),          # (L, kv_dim->tp, dim)
    "wv": P(None, AXIS_TP),
    # merged matvec groups (models/params.py fuse_matvec_groups): rows are
    # TP-group interleaved at fuse time, so plain row sharding lands each shard
    # its own [q|k|v] / [gate|up] block
    "wqkv": P(None, AXIS_TP),        # (L, (dim+2kv)->tp, dim)
    "w13": P(None, AXIS_TP),         # (L, 2*hidden->tp, dim)
    "wo": P(None, None, AXIS_TP),    # (L, dim, dim->tp) partial-sum
    "w1": P(None, AXIS_TP),          # (L, hidden->tp, dim)
    "w3": P(None, AXIS_TP),
    "w2": P(None, None, AXIS_TP),    # (L, dim, hidden->tp) partial-sum
    "router": P(),                    # replicated (root-only in reference)
    "moe_up": P(None, None, AXIS_TP),    # (L, E, hidden->tp, dim)
    "moe_gate": P(None, None, AXIS_TP),
    "moe_gu": P(None, None, AXIS_TP),    # (L, E, 2*hidden->tp, dim), merged up+gate
    "moe_down": P(None, None, None, AXIS_TP),  # (L, E, dim, hidden->tp)
    "rms_att": P(),
    "rms_ffn": P(),
    "rms_moe": P(),
    "rms_ffn2": P(),
}


# expert parallelism: the MoE stacks shard by WHOLE experts over the tp axis
# instead of slicing every expert's hidden dim. Each shard owns E/tp complete
# experts; a decode step streams only the active experts' weights on their owner
# shards, and the existing FFN-output psum merges contributions. This is the
# capacity axis for MoE models whose expert weights dwarf one chip's HBM
# (Grok-1-314B class) — the reference has no counterpart (it always slices).
_EP_SPECS = {
    "moe_up": P(None, AXIS_TP),    # (L, E->tp, hidden, dim), experts whole
    "moe_gate": P(None, AXIS_TP),
    "moe_gu": P(None, AXIS_TP),    # (L, E->tp, 2*hidden, dim), merged up+gate
    "moe_down": P(None, AXIS_TP),  # (L, E->tp, dim, hidden)
}


def param_pspecs(params: dict[str, Any],
                 moe_sharding: str = "slice") -> dict[str, Any]:
    """PartitionSpec pytree (prefix) matching a params dict.

    moe_sharding: "slice" (hidden-dim TP inside every expert, the default) or
    "expert" (whole experts over tp — see _EP_SPECS)."""
    assert moe_sharding in ("slice", "expert"), moe_sharding
    blocks = {k: _BLOCK_SPECS[k] for k in params["blocks"]}
    if moe_sharding == "expert":
        blocks.update({k: v for k, v in _EP_SPECS.items() if k in blocks})
    return {
        "embedding": P(),  # replicated, root-only-F32 in reference (transformer.cpp:496)
        "blocks": blocks,
        "rms_final": P(),
        "wcls": P(AXIS_TP),  # (vocab->tp, dim); logits all-gathered in forward
    }


def kv_cache_pspec(seq_axis: str | None = None) -> P:
    """Cache (L, B, hk, S, hs): batch on dp, heads on tp (KvCacheSlice),
    optionally S on sp. dp/sp of size 1 make those entries no-ops."""
    from .mesh import AXIS_DP

    return P(None, AXIS_DP, AXIS_TP, seq_axis)


def kv_cache_pspec_for_mesh(mesh) -> P:
    """Cache pspec for a mesh: sequence axis sharded iff the mesh has sp > 1."""
    from .mesh import AXIS_SP

    return kv_cache_pspec(AXIS_SP if mesh.shape.get(AXIS_SP, 1) > 1 else None)


def effective_kv_heads(spec: ModelSpec, tp: int) -> int:
    """KV-head count after TP replication.

    The reference hard-fails when nSlices > nKvHeads (transformer.cpp:108-111), which
    blocks 405B-class GQA models (8 KV heads) on pods with 16+ chips. Here the standard
    GQA trick lifts the limit: when tp > n_kv_heads, each KV head is replicated across
    tp/n_kv_heads adjacent shards (shard j holds KV head j*n_kv_heads//tp), so every
    shard's query-head slice finds its KV head locally. wk/wv rows and the KV cache head
    axis are expanded to `tp` heads at distribution time (parallel/tp.py shard_params).
    """
    if tp <= spec.n_kv_heads:
        return spec.n_kv_heads
    assert tp % spec.n_kv_heads == 0, (
        f"tp={tp} must be a multiple of n_kv_heads={spec.n_kv_heads} to replicate "
        "KV heads evenly")
    return tp


def check_divisibility(spec: ModelSpec, tp: int, sp: int = 1,
                       moe_sharding: str = "slice") -> None:
    """Even-division checks that replace the reference's 2^n assumption and its
    nSlices <= nKvHeads limit (transformer.cpp:108-111; lifted via KV-head
    replication, see effective_kv_heads)."""
    hk = effective_kv_heads(spec, tp)  # asserts tp % n_kv_heads when replicating
    assert hk % tp == 0, (
        f"tp={tp} must divide n_kv_heads={spec.n_kv_heads} (or be a multiple of it "
        "for KV-head replication)")
    assert spec.n_heads % tp == 0, (
        f"tp={tp} must divide n_heads={spec.n_heads}")
    assert spec.dim % tp == 0
    assert spec.vocab_size % tp == 0
    if (spec.dim // tp) % 32:
        raise AssertionError("tp slice must keep 32-wide quant blocks intact")
    if moe_sharding == "expert" and spec.is_moe:
        assert spec.n_experts % tp == 0, (
            f"expert sharding: tp={tp} must divide n_experts={spec.n_experts}")
    else:
        # hidden dim is TP-sliced (dense FFN always; MoE experts in slice mode)
        assert spec.hidden_dim % tp == 0
        if (spec.hidden_dim // tp) % 32:
            raise AssertionError("tp slice must keep 32-wide quant blocks intact")
    assert spec.seq_len % sp == 0, (
        f"sp={sp} must divide seq_len={spec.seq_len} (sequence-sharded KV cache)")
