from .mesh import make_mesh  # noqa: F401
from .sharding import effective_kv_heads, kv_cache_pspec, param_pspecs  # noqa: F401
from .tp import make_sharded_forward, shard_params  # noqa: F401
