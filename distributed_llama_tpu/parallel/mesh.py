"""Device-mesh construction.

The reference's cluster topology is a TCP star of 2^n hosts (--workers host:port...,
socket.cpp:160-185). Here the topology is a jax.sharding.Mesh over TPU chips with named
axes:

    dp — data parallel (independent sequences; no reference equivalent, batch was 1)
    sp — sequence parallel (ring attention over the KV sequence axis; reference: absent)
    tp — tensor parallel (the reference's nSlices axis)

Collectives ride ICI when the mesh axes are laid out within a slice, DCN across slices —
XLA handles placement; we only pick axis sizes. The reference's 2^n-nodes restriction
(README.md:33-34) disappears: any divisor layout works.

A fourth capacity strategy, expert parallelism, rides the tp axis rather than adding a
mesh axis: moe_sharding="expert" (parallel/sharding.py) shards WHOLE experts over tp
while attention stays head-sharded — same mesh, different PartitionSpecs.

Pipeline parallelism is deliberately absent: for autoregressive DECODE a layer
pipeline serializes on the single in-flight token (the bubble is the whole pipeline),
and on TPU the per-layer all-reduce that tp costs rides ICI at full bandwidth, so tp
(+ ep for MoE capacity, + sp for context capacity) dominates pp at every scale the
BASELINE targets — including 405B on a v5p-16, which fits tp=16 across the slice.
pp earns its bubbles only in throughput-batch prefill/training regimes the reference
(and this framework's serving focus) does not target.
"""

from __future__ import annotations

import numpy as np
import jax
from jax.sharding import Mesh

AXIS_DP, AXIS_SP, AXIS_TP = "dp", "sp", "tp"


def make_mesh(tp: int | None = None, sp: int = 1, dp: int = 1,
              devices: list | None = None) -> Mesh:
    """Build a (dp, sp, tp) mesh. Defaults: all devices on tp."""
    devs = devices if devices is not None else jax.devices()
    n = len(devs)
    if tp is None:
        assert n % (sp * dp) == 0, (n, sp, dp)
        tp = n // (sp * dp)
    need = dp * sp * tp
    assert need <= n, f"mesh {dp}x{sp}x{tp} needs {need} devices, have {n}"
    grid = np.array(devs[:need]).reshape(dp, sp, tp)
    return Mesh(grid, (AXIS_DP, AXIS_SP, AXIS_TP))


def init_multihost(coordinator: str | None = None, num_processes: int | None = None,
                   process_id: int | None = None) -> int:
    """Join a multi-host TPU pod job (the SPMD replacement for the reference's
    `dllama worker --port ...` + `--workers host:port ...` bootstrap,
    src/apps/dllama/dllama.cpp:205-221).

    Every host runs the SAME program; jax.distributed wires them into one runtime.
    On Cloud TPU pods all three arguments come from the metadata server, so plain
    `init_multihost()` suffices; elsewhere pass coordinator="host0:1234",
    num_processes and process_id explicitly. Returns this host's process index.
    """
    kw = {k: v for k, v in (("coordinator_address", coordinator),
                            ("num_processes", num_processes),
                            ("process_id", process_id)) if v is not None}
    jax.distributed.initialize(**kw)
    return jax.process_index()


def make_pod_mesh(tp: int | None = None, sp: int = 1, dp: int | None = None) -> Mesh:
    """DCN-aware (dp, sp, tp) mesh over every chip in a multi-host job.

    Axis placement follows the bandwidth hierarchy: tp (all-reduce per layer —
    the heaviest traffic, tasks.cpp:44-94's broadcast/gather pattern) and sp
    (ring permutes) stay INSIDE an ICI domain; dp (independent sequences, no
    per-step traffic) spans ICI domains over DCN. This is the standard
    ici/dcn hybrid-mesh recipe; the reference's 1 GbE star forced ALL traffic
    over the slow link, which is why its 8-node numbers collapse
    (reference README.md:122).

    The ICI domain is a pod SLICE, not a host: on a v5p-16 (4 hosts, one slice)
    every chip is ICI-connected, so tp=16 across all 4 hosts is the right layout
    — the BASELINE.json 405B north-star config. Only MULTISLICE jobs (devices
    reporting distinct slice_index) have a DCN boundary, and there dp must span
    the slices.
    """
    from jax.experimental import mesh_utils

    devs = jax.devices()  # global: every chip in the job, all processes
    n_total = len(devs)
    n_slices = len({getattr(d, "slice_index", 0) for d in devs})
    if tp is None:
        dp = dp if dp is not None else n_slices
        assert n_total % (dp * sp) == 0, (n_total, dp, sp)
        tp = n_total // (dp * sp)
    elif dp is None:
        assert n_total % (sp * tp) == 0, (n_total, sp, tp)
        dp = n_total // (sp * tp)
    assert dp * sp * tp == n_total, (dp, sp, tp, n_total)
    if n_slices == 1:
        # one ICI domain (single- or multi-host). create_device_mesh reorders the
        # devices so mesh neighbors are torus neighbors — raw jax.devices()
        # enumeration order would let the per-layer all-reduce ring cross the ICI
        # torus non-contiguously on multi-host slices (e.g. v5p-16 tp=16).
        try:
            grid = mesh_utils.create_device_mesh((dp, sp, tp), devices=devs)
            return Mesh(grid, (AXIS_DP, AXIS_SP, AXIS_TP))
        except (ValueError, NotImplementedError, AssertionError):
            # non-TPU platforms / shapes create_device_mesh cannot map: plain order
            return make_mesh(tp=tp, sp=sp, dp=dp, devices=devs)
    assert dp % n_slices == 0, (
        f"dp={dp} must span the {n_slices} slices (tp/sp must fit inside one "
        f"slice: {sp * tp} chips vs {n_total // n_slices} per slice)")
    grid = mesh_utils.create_hybrid_device_mesh(
        mesh_shape=(dp // n_slices, sp, tp), dcn_mesh_shape=(n_slices, 1, 1))
    return Mesh(grid, (AXIS_DP, AXIS_SP, AXIS_TP))
