"""Device-mesh construction.

The reference's cluster topology is a TCP star of 2^n hosts (--workers host:port...,
socket.cpp:160-185). Here the topology is a jax.sharding.Mesh over TPU chips with named
axes:

    dp — data parallel (independent sequences; no reference equivalent, batch was 1)
    sp — sequence parallel (ring attention over the KV sequence axis; reference: absent)
    tp — tensor parallel (the reference's nSlices axis)

Collectives ride ICI when the mesh axes are laid out within a slice, DCN across slices —
XLA handles placement; we only pick axis sizes. The reference's 2^n-nodes restriction
(README.md:33-34) disappears: any divisor layout works.
"""

from __future__ import annotations

import numpy as np
import jax
from jax.sharding import Mesh

AXIS_DP, AXIS_SP, AXIS_TP = "dp", "sp", "tp"


def make_mesh(tp: int | None = None, sp: int = 1, dp: int = 1,
              devices: list | None = None) -> Mesh:
    """Build a (dp, sp, tp) mesh. Defaults: all devices on tp."""
    devs = devices if devices is not None else jax.devices()
    n = len(devs)
    if tp is None:
        assert n % (sp * dp) == 0, (n, sp, dp)
        tp = n // (sp * dp)
    need = dp * sp * tp
    assert need <= n, f"mesh {dp}x{sp}x{tp} needs {need} devices, have {n}"
    grid = np.array(devs[:need]).reshape(dp, sp, tp)
    return Mesh(grid, (AXIS_DP, AXIS_SP, AXIS_TP))
