"""Measured collective traffic: exact per-execution accounting of the step program.

The reference counts real socket bytes per token (src/socket.cpp:280-285) and prints
them as the S/R columns (dllama.cpp:76-93). A TPU program's transfers are the
collective ops in the compiled step, so the honest equivalent is to account each
collective the program executes — not an analytic formula that assumes which ops
exist (runtime/engine.py keeps that formula, explicitly labeled "modeled", for when
no compiled step is available).

Two accounting paths:

- `jaxpr_collective_traffic` — walks the traced step jaxpr, recursing into scan /
  while / cond / pjit / shard_map and multiplying by scan trip counts, so a psum
  inside the layer scan is counted n_layers times per execution. This is the primary
  path: exact bytes per dispatch, including loop bodies that appear only once in the
  HLO module text.
- `collective_traffic` — parses an HLO module text per instruction (XLA's chosen
  async/combined forms). Static module view: loop bodies count once.

Per-device wire-byte accounting uses the standard ring-algorithm costs:

    all-reduce        payload P          sends 2 (n-1)/n * P
    all-gather        output P           sends (n-1)/n * P   (each shard passed n-1 hops)
    reduce-scatter    output P           sends (n-1) * P     (input = n * P)
    all-to-all        payload P          sends (n-1)/n * P
    collective-permute payload P         sends P

where n is the group size (replica_groups in HLO; mesh axis sizes in the jaxpr).
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Any

_DTYPE_BYTES = {
    "pred": 1, "s4": 1, "u4": 1, "s8": 1, "u8": 1, "f8e4m3fn": 1, "f8e5m2": 1,
    "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4, "s64": 8, "u64": 8, "f64": 8,
}

# async collectives appear as <op>-start / <op>-done pairs; count only the -start
# (or the bare sync op) so each transfer is accounted once
_COLLECTIVES = ("all-reduce", "all-gather", "reduce-scatter", "all-to-all",
                "collective-permute")

_SHAPE_RE = re.compile(r"([a-z0-9]+)\[([\d,]*)\]")
_GROUPS_RE = re.compile(r"replica_groups=\{\{([\d,]+)\}")
_GROUPS_IOTA_RE = re.compile(r"replica_groups=\[(\d+),(\d+)\]")


def _shape_bytes(shape_str: str) -> int:
    total = 0
    for dt, dims in _SHAPE_RE.findall(shape_str):
        if dt not in _DTYPE_BYTES:
            continue
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        total += n * _DTYPE_BYTES[dt]
    return total


def _group_size(line: str, default: int) -> int:
    m = _GROUPS_RE.search(line)
    if m:
        return len(m.group(1).split(","))
    m = _GROUPS_IOTA_RE.search(line)  # iota format: replica_groups=[ngroups,size]<=[n]
    if m:
        return int(m.group(2))
    return default


def _sent_factor(op: str, n: int) -> float:
    if n <= 1:
        return 0.0
    if op == "all-reduce":
        return 2.0 * (n - 1) / n
    if op == "all-gather":
        return (n - 1) / n
    if op == "reduce-scatter":
        return float(n - 1)
    if op == "all-to-all":
        return (n - 1) / n
    return 1.0  # collective-permute


@dataclass
class CollectiveTraffic:
    """Per-dispatch collective accounting (one compiled program execution)."""

    counts: dict[str, int] = field(default_factory=dict)
    payload_bytes: dict[str, int] = field(default_factory=dict)
    sent_bytes_per_device: float = 0.0  # == received, for the ring algorithms above

    @property
    def total_payload_bytes(self) -> int:
        return sum(self.payload_bytes.values())


_JAXPR_COLLECTIVES = {
    "psum": "all-reduce",
    "all_gather": "all-gather",
    "reduce_scatter": "reduce-scatter",
    "all_to_all": "all-to-all",
    "ppermute": "collective-permute",
}


def _axes_size(params: dict, axis_sizes: dict[str, int]) -> int:
    axes = params.get("axes") or params.get("axis_name") or ()
    if not isinstance(axes, (tuple, list)):
        axes = (axes,)
    n = 1
    for a in axes:
        n *= axis_sizes.get(a, 1) if isinstance(a, str) else 1
    return n


def _merge(dst: CollectiveTraffic, src: CollectiveTraffic, mult: int) -> None:
    for op, c in src.counts.items():
        dst.counts[op] = dst.counts.get(op, 0) + c * mult
    for op, b in src.payload_bytes.items():
        dst.payload_bytes[op] = dst.payload_bytes.get(op, 0) + b * mult
    dst.sent_bytes_per_device += src.sent_bytes_per_device * mult


def _walk_jaxpr(jaxpr, axis_sizes: dict[str, int], mult: int,
                out: CollectiveTraffic) -> None:
    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        if name in _JAXPR_COLLECTIVES:
            op = _JAXPR_COLLECTIVES[name]
            payload = sum(v.aval.size * v.aval.dtype.itemsize for v in eqn.outvars)
            n = _axes_size(eqn.params, axis_sizes)
            out.counts[op] = out.counts.get(op, 0) + mult
            out.payload_bytes[op] = out.payload_bytes.get(op, 0) + payload * mult
            out.sent_bytes_per_device += _sent_factor(op, n) * payload * mult
            continue
        if name == "cond":
            # only one branch executes per dispatch: account the heaviest branch
            # rather than summing both (which would overstate traffic)
            branch_traffic = []
            for pval in eqn.params.values():
                for sub in _sub_jaxprs(pval):
                    t = CollectiveTraffic()
                    _walk_jaxpr(sub, axis_sizes, 1, t)
                    branch_traffic.append(t)
            if branch_traffic:
                worst = max(branch_traffic, key=lambda t: t.sent_bytes_per_device)
                _merge(out, worst, mult)
            continue
        # recurse into sub-jaxprs, multiplying by loop trip counts
        sub_mult = mult
        if name == "scan":
            sub_mult = mult * int(eqn.params.get("length", 1))
        for pval in eqn.params.values():
            for sub in _sub_jaxprs(pval):
                _walk_jaxpr(sub, axis_sizes, sub_mult, out)


def _sub_jaxprs(pval: Any):
    import jax.extend.core as jex_core

    if isinstance(pval, jex_core.ClosedJaxpr):
        yield pval.jaxpr
    elif isinstance(pval, jex_core.Jaxpr):
        yield pval
    elif isinstance(pval, (tuple, list)):
        for item in pval:
            yield from _sub_jaxprs(item)


def jaxpr_collective_traffic(closed_jaxpr, axis_sizes: dict[str, int]
                             ) -> CollectiveTraffic:
    """Exact per-execution collective accounting of a traced step program.

    `axis_sizes` maps mesh axis names to sizes (mesh.shape). Counts reflect one
    execution of the program: collectives inside lax.scan bodies are multiplied by
    the scan length; lax.cond contributes its heaviest branch (only one runs);
    while-loop bodies, whose trip counts are data-dependent, are counted once per
    entry."""
    out = CollectiveTraffic()
    _walk_jaxpr(closed_jaxpr.jaxpr, dict(axis_sizes), 1, out)
    return out


def publish_traffic(traffic: CollectiveTraffic, program: str) -> None:
    """Export a program's measured collective accounting as gauges
    (obs/metrics.py) so `GET /metrics` serves what was previously a one-off
    bench artifact. `program` names the compiled program the numbers belong
    to (e.g. "decode_t1") — per-program provenance is the whole point of the
    measured path (presenting one program's trace as another's was the
    round-1 defect)."""
    from ..obs import metrics

    metrics.gauge(
        "collective_sent_bytes_per_device",
        "Measured per-device ring-algorithm wire bytes per program execution",
        labelnames=("program",)).labels(program=program).set(
            traffic.sent_bytes_per_device)
    payload = metrics.gauge(
        "collective_payload_bytes",
        "Measured collective payload bytes per program execution, by op",
        labelnames=("program", "op"))
    count = metrics.gauge(
        "collective_count",
        "Collective ops executed per program execution, by op",
        labelnames=("program", "op"))
    for op, b in traffic.payload_bytes.items():
        payload.labels(program=program, op=op).set(b)
    for op, c in traffic.counts.items():
        count.labels(program=program, op=op).set(c)


def collective_traffic(hlo_text: str, default_group_size: int) -> CollectiveTraffic:
    """Account every collective instruction in an (optimized) HLO module text.

    `default_group_size` is used when an instruction carries no parseable
    replica_groups (e.g. empty groups meaning "all devices").
    """
    out = CollectiveTraffic()
    for line in hlo_text.splitlines():
        line = line.strip()
        # instruction form: %name = SHAPE opcode(...), ...
        m = re.match(r"%?[\w.\-]+ = (.+?) ([a-z0-9\-]+)\(", line)
        if not m:
            continue
        shape_str, op = m.group(1), m.group(2)
        if op.endswith("-done"):
            continue  # async completion: transfer already counted at its -start
        is_start = op.endswith("-start")
        if is_start:
            op = op[: -len("-start")]
        if op not in _COLLECTIVES:
            continue
        if is_start and "(" in shape_str:
            # async-start outputs are (operand, result, ...) tuples; the result
            # (last element) is the transferred payload
            dt, dims = _SHAPE_RE.findall(shape_str)[-1]
            payload = _DTYPE_BYTES.get(dt, 0)
            for d in dims.split(","):
                if d:
                    payload *= int(d)
        else:
            payload = _shape_bytes(shape_str)
        n = _group_size(line, default_group_size)
        out.counts[op] = out.counts.get(op, 0) + 1
        out.payload_bytes[op] = out.payload_bytes.get(op, 0) + payload
        out.sent_bytes_per_device += _sent_factor(op, n) * payload
    return out
