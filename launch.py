#!/usr/bin/env python
"""Model-zoo launcher: download pre-converted `.m`/`.t` models and run the TPU CLI.

Counterpart of the reference launch.py (model zoo at launch.py:14-40) — same public
pre-converted checkpoints (the file formats are byte-compatible), multi-part downloads
for the 405B split, and a generated run script that invokes the TPU CLI instead of the
reference's dllama binary.

Usage: python launch.py <model-name> [--tp N] [--run]
       python launch.py --list
"""

from __future__ import annotations

import argparse
import os
import sys
import urllib.request


def _parts(length: int) -> list[str]:
    return [chr(97 + i // 26) + chr(97 + i % 26) for i in range(length)]


_HF = "https://huggingface.co/b4rtaz"

# name -> (model urls, tokenizer url, weights ftype, buffer ftype, mode)
MODELS: dict[str, tuple[list[str], str, str, str, str]] = {
    "tinyllama_1_1b_3t_q40": (
        [f"{_HF}/TinyLlama-1.1B-3T-Distributed-Llama/resolve/main/dllama_model_tinylama_1.1b_3t_q40.m?download=true"],
        f"{_HF}/TinyLlama-1.1B-3T-Distributed-Llama/resolve/main/dllama_tokenizer_tinylama_1.1b_3t.t?download=true",
        "q40", "q80", "base"),
    "llama3_8b_q40": (
        [f"{_HF}/Llama-3-8B-Q40-Distributed-Llama/resolve/main/dllama_model_meta-llama-3-8b_q40.m?download=true"],
        f"{_HF}/Llama-3-8B-Q40-Distributed-Llama/resolve/main/dllama_tokenizer_llama3.t?download=true",
        "q40", "q80", "base"),
    "llama3_8b_instruct_q40": (
        [f"{_HF}/Llama-3-8B-Q40-Instruct-Distributed-Llama/resolve/main/dllama_model_lama3_instruct_q40.m?download=true"],
        f"{_HF}/Llama-3-8B-Q40-Instruct-Distributed-Llama/resolve/main/dllama_tokenizer_llama3.t?download=true",
        "q40", "q80", "chat"),
    "llama3_1_8b_instruct_q40": (
        [f"{_HF}/Llama-3_1-8B-Q40-Instruct-Distributed-Llama/resolve/main/dllama_model_llama3.1_instruct_q40.m?download=true"],
        f"{_HF}/Llama-3_1-8B-Q40-Instruct-Distributed-Llama/resolve/main/dllama_tokenizer_llama_3_1.t?download=true",
        "q40", "q80", "chat"),
    "llama3_1_405b_instruct_q40": (
        [f"{_HF}/Llama-3_1-405B-Q40-Instruct-Distributed-Llama/resolve/main/dllama_model_llama31_405b_q40_{s}?download=true"
         for s in _parts(56)],
        f"{_HF}/Llama-3_1-405B-Q40-Instruct-Distributed-Llama/resolve/main/dllama_tokenizer_llama_3_1.t?download=true",
        "q40", "q80", "chat"),
}


def download(urls: list[str], path: str) -> None:
    if os.path.isfile(path):
        print(f"✅ {path} already exists")
        return
    tmp = path + ".part"
    with open(tmp, "wb") as out:
        for url in urls:
            print(f"📄 {url}")
            with urllib.request.urlopen(url) as resp:
                while True:
                    chunk = resp.read(1 << 20)
                    if not chunk:
                        break
                    out.write(chunk)
                    sys.stdout.write(f"\rDownloaded {out.tell() >> 20} MB")
            sys.stdout.write("\n")
    os.replace(tmp, path)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("model", nargs="?")
    ap.add_argument("--list", action="store_true")
    ap.add_argument("--tp", type=int, default=None)
    ap.add_argument("--run", action="store_true", help="run after download")
    ap.add_argument("--dir", default="models")
    args = ap.parse_args()

    if args.list or not args.model:
        print("Available models:")
        for name in MODELS:
            print(f"  {name}")
        return
    if args.model not in MODELS:
        sys.exit(f"unknown model {args.model!r}; use --list")

    urls, tok_url, wft, bft, mode = MODELS[args.model]
    os.makedirs(os.path.join(args.dir, args.model), exist_ok=True)
    mpath = os.path.join(args.dir, args.model, f"dllama_model_{args.model}.m")
    tpath = os.path.join(args.dir, args.model, f"dllama_tokenizer_{args.model}.t")
    download(urls, mpath)
    download([tok_url], tpath)

    cli_mode = "chat" if mode == "chat" else "inference"
    cmd = (f"python -m distributed_llama_tpu.apps.dllama {cli_mode} "
           f"--model {mpath} --tokenizer {tpath} "
           f"--weights-float-type {wft} --buffer-float-type {bft} --max-seq-len 4096"
           + (f" --tp {args.tp}" if args.tp else ""))
    script = f"run_{args.model}.sh"
    with open(script, "w") as f:
        f.write("#!/bin/sh\n" + cmd + "\n")
    os.chmod(script, 0o755)
    print(f"📜 wrote {script}")
    if args.run:
        os.execvp("sh", ["sh", script])


if __name__ == "__main__":
    main()
