#!/usr/bin/env python
"""Build a small REAL-FORMAT Q40 checkpoint + byte-level tokenizer for the examples.

The container the framework is developed in has zero network egress, so the model zoo
(launch.py) is unreachable; this builds a Llama-architecture model through the same
file-format path a converted checkpoint takes (formats.mfile / formats.tfile — the
byte-compatible `.m`/`.t` writers the converter uses), with deterministic seeded
weights. Everything downstream of conversion — header parse, tensor mmap, Q40
dequant, engine, tokenizer — is exactly the real-checkpoint code path.

Usage: python examples/make_tiny_model.py [outdir]
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from distributed_llama_tpu.formats.mfile import params_file_order, write_model
from distributed_llama_tpu.formats.tfile import TokenizerData, write_tokenizer
from distributed_llama_tpu.models.params import init_random_params
from distributed_llama_tpu.models.spec import ArchType, ModelSpec, RopeType
from distributed_llama_tpu.quants import FloatType


def main(outdir: str = "/tmp/dlt_determinism") -> None:
    os.makedirs(outdir, exist_ok=True)
    spec = ModelSpec(arch_type=ArchType.LLAMA, dim=256, hidden_dim=512, n_layers=4,
                     n_heads=8, n_kv_heads=4, vocab_size=260, seq_len=1024,
                     rope_type=RopeType.LLAMA).resolved()
    params = init_random_params(spec, FloatType.Q40, seed=20260729)
    write_model(os.path.join(outdir, "tiny.m"), spec,
                params_file_order(spec, params), FloatType.Q40)

    # byte-level tokenizer: ids 3..258 are the 256 raw bytes, so any prompt encodes
    # via the reference's +3 byte-fallback rule (tokenizer.cpp:247-253)
    vocab = [b"<unk>", b"<s>", b"</s>"] + [bytes([i]) for i in range(256)] + [b"<pad>"]
    scores = [0.0] * len(vocab)
    td = TokenizerData(vocab=vocab, scores=scores, bos_id=1, eos_id=2,
                       chat_template="{% llama2 %}[INST] {{content}} [/INST]")
    write_tokenizer(os.path.join(outdir, "tiny.t"), td)
    print(f"wrote {outdir}/tiny.m ({os.path.getsize(os.path.join(outdir, 'tiny.m'))} B) "
          f"and {outdir}/tiny.t")


if __name__ == "__main__":
    main(*sys.argv[1:])
