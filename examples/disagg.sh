#!/bin/bash
# Prefill/decode disaggregation demo (docs/DISAGG.md): a prefill-role and a
# decode-role replica behind the router with the splitter armed. The long
# system prompt's prefill runs on the prefill replica, its KV blocks ship
# over /v1/kv, and the decode replica admits with zero re-prefill of the
# shipped span — watch the split/import/re-prefill counters at the end.
set -e
cd "$(dirname "$0")/.."

MODEL="${DLLAMA_MODEL:-/tmp/dlt_determinism/tiny.m}"
TOKENIZER="${DLLAMA_TOKENIZER:-/tmp/dlt_determinism/tiny.t}"
if [ ! -f "$MODEL" ]; then
  mkdir -p /tmp/dlt_determinism
  python examples/make_tiny_model.py /tmp/dlt_determinism
fi

export JAX_PLATFORMS=cpu
PORT_P="${PORT_P:-9991}"
PORT_D="${PORT_D:-9992}"
ROUTER_PORT="${ROUTER_PORT:-9993}"

LOGDIR="$(mktemp -d /tmp/dlt_disagg_demo.XXXXXX)"
python -m distributed_llama_tpu.apps.api_server \
  --model "$MODEL" --tokenizer "$TOKENIZER" --chat-template chatml \
  --host 127.0.0.1 --port "$PORT_P" --batch 2 --superstep 4 \
  --role prefill >"$LOGDIR/prefill.log" 2>&1 &
python -m distributed_llama_tpu.apps.api_server \
  --model "$MODEL" --tokenizer "$TOKENIZER" --chat-template chatml \
  --host 127.0.0.1 --port "$PORT_D" --batch 2 --superstep 4 \
  --role decode >"$LOGDIR/decode.log" 2>&1 &
python -m distributed_llama_tpu.apps.router \
  --replica "127.0.0.1:$PORT_P" --replica "127.0.0.1:$PORT_D" \
  --host 127.0.0.1 --port "$ROUTER_PORT" --poll-interval 0.5 \
  --disagg-threshold 32 >"$LOGDIR/router.log" 2>&1 &
SERVER_PIDS="$(jobs -p)"
trap 'kill $SERVER_PIDS 2>/dev/null || true' EXIT

for _ in $(seq 600); do
  IN_ROT=$(curl -s "http://127.0.0.1:$ROUTER_PORT/healthz" 2>/dev/null |
    python -c 'import json,sys; print(json.load(sys.stdin).get("in_rotation", 0))' \
      2>/dev/null || echo 0)
  [ "$IN_ROT" = "2" ] && break
  sleep 1
done
echo "— fleet up: $IN_ROT replicas (prefill :$PORT_P, decode :$PORT_D)"

LONG_SYSTEM="You are a meticulous assistant. This long system preamble \
stands in for the retrieval context a production request drags along: the \
quick brown fox jumps over the lazy dog, again and again and again, while \
the five boxing wizards jump quickly and the jay, pig, fox, zebra and my \
wolves quack; sphinx of black quartz, judge my vow."

req() {
  curl -s "http://127.0.0.1:$ROUTER_PORT/v1/chat/completions" \
    -H 'Content-Type: application/json' \
    -d "{\"messages\": [{\"role\": \"system\", \"content\": \"$1\"},
                        {\"role\": \"user\", \"content\": \"$2\"}],
         \"max_tokens\": 12, \"temperature\": 0}" >/dev/null
  echo "  client done: $2"
}

echo "— long-prompt requests (each splits: prefill replica -> KV wire -> decode replica)"
req "$LONG_SYSTEM" "summarize the preamble"
req "$LONG_SYSTEM different tail so nothing is radix-shared $(date +%N)" "and again"

echo "— a short decode chain (below the threshold: routed straight to the decode replica)"
req "" "just say hi"

echo "— disaggregation counters:"
curl -s "http://127.0.0.1:$ROUTER_PORT/v1/stats" | python -c '
import json, sys
stats = json.load(sys.stdin)
routes = stats["router"]["metrics"].get("router_disagg_requests_total", {})
print("  router split decisions:", routes or "(none)")
for rep_id, st in sorted(stats.get("replicas", {}).items()):
    m = st.get("metrics") or {}
    dis = st.get("disagg") or {}
    pre = m.get("disagg_prefill_requests_total")
    imp = m.get("disagg_import_requests_total")
    rep_tok = m.get("disagg_reprefill_tokens_total", 0)
    print("  replica %s role=%s prefills=%s imports=%s reprefill_tokens=%s"
          % (rep_id, dis.get("role"), pre, imp, rep_tok))
'
