#!/bin/bash
# Constrained-decoding demo (docs/SERVING.md "Constrained decoding"): pin
# completions to a grammar with `response_format` — a JSON Schema and a
# regex, both lowered to token-mask automata enforced ON DEVICE next to an
# unconstrained co-batched request. A malformed grammar is refused with an
# honest 400 before any queue work; watch the constrain_* counters and the
# /v1/stats constrain block move.
set -e
cd "$(dirname "$0")/.."

MODEL="${DLLAMA_MODEL:-/tmp/dlt_determinism/tiny.m}"
TOKENIZER="${DLLAMA_TOKENIZER:-/tmp/dlt_determinism/tiny.t}"
if [ ! -f "$MODEL" ]; then
  mkdir -p /tmp/dlt_determinism
  python examples/make_tiny_model.py /tmp/dlt_determinism
fi

export JAX_PLATFORMS=cpu
PORT="${PORT:-9994}"

python -m distributed_llama_tpu.apps.api_server \
  --model "$MODEL" --tokenizer "$TOKENIZER" --chat-template chatml \
  --host 127.0.0.1 --port "$PORT" --batch 2 --superstep 4 --speculative 8 &
SERVER_PID=$!
trap 'kill $SERVER_PID 2>/dev/null || true' EXIT

for _ in $(seq 60); do
  curl -sf "http://127.0.0.1:$PORT/healthz" >/dev/null 2>&1 && break
  sleep 1
done

echo "— json_schema: output is forced to a record shape (keys forced, values chosen)"
curl -s "http://127.0.0.1:$PORT/v1/chat/completions" \
  -H 'Content-Type: application/json' \
  -d '{"messages": [{"role": "user", "content": "emit a sensor reading"}],
       "max_tokens": 48, "temperature": 0,
       "response_format": {"type": "json_schema", "json_schema": {"schema":
         {"type": "object", "properties": {
            "sensor": {"enum": ["alpha", "beta"]},
            "ok": {"type": "boolean"}}}}}}' \
  | python -c 'import json,sys; print("  ", json.load(sys.stdin)["choices"][0]["message"]["content"])'

echo "— regex: a fixed-shape id, stochastic sampling inside the mask"
curl -s "http://127.0.0.1:$PORT/v1/chat/completions" \
  -H 'Content-Type: application/json' \
  -d '{"messages": [{"role": "user", "content": "make an id"}],
       "max_tokens": 24, "temperature": 0.8, "seed": 7,
       "response_format": {"type": "regex", "regex": "[a-f]{4}-[0-9]{4}"}}' \
  | python -c 'import json,sys; print("  ", json.load(sys.stdin)["choices"][0]["message"]["content"])'

echo "— malformed grammar: an honest 400 BEFORE any queue work"
curl -s "http://127.0.0.1:$PORT/v1/chat/completions" \
  -H 'Content-Type: application/json' \
  -d '{"messages": [{"role": "user", "content": "x"}], "max_tokens": 8,
       "response_format": {"type": "regex", "regex": "[unclosed"}}' \
  | python -c 'import json,sys; e=json.load(sys.stdin)["error"]; print("  ", e["type"], "-", e["message"])'

echo "— /v1/stats constrain block:"
curl -s "http://127.0.0.1:$PORT/v1/stats" | python -c '
import json, sys
c = json.load(sys.stdin).get("constrain", {})
for k in ("active_rows", "table_states", "table_used", "degraded"):
    print(f"  {k}: {c.get(k)}")
comp = c.get("compile")
print(f"  compile: {comp}")
'
