#!/bin/bash
# KV-cache-filling determinism check — the counterpart of the reference's
# examples/macbeth.sh (long greedy generation over a long prompt must reproduce the
# exact same token sequence run over run).
#
# The reference runs against the downloaded Llama-3-8B checkpoint and notes its output
# is only stable on one CPU family. Here, by default, the check runs against a
# real-format Q40 checkpoint with seeded weights built by examples/make_tiny_model.py
# (this container has zero egress, so the model zoo is unreachable); the whole
# pipeline — converter-format .m/.t files, engine, windowed attention, tokenizer,
# greedy sampler — is exercised and the output asserted stable across two runs and
# against the committed expectation for the CPU backend.
#
# With a real checkpoint available (python launch.py tinyllama_1_1b_3t_q40), point
# DLLAMA_MODEL/DLLAMA_TOKENIZER at it and the same determinism contract applies.
set -e
cd "$(dirname "$0")/.."

MODEL="${DLLAMA_MODEL:-/tmp/dlt_determinism/tiny.m}"
TOKENIZER="${DLLAMA_TOKENIZER:-/tmp/dlt_determinism/tiny.t}"
STEPS="${DLLAMA_STEPS:-96}"

if [ ! -f "$MODEL" ]; then
  mkdir -p /tmp/dlt_determinism
  python examples/make_tiny_model.py /tmp/dlt_determinism
fi

PROMPT="The quick brown fox jumps over the lazy dog while seventy silent engineers
measure the bandwidth of a systolic array at dawn. Every block of thirty-two nibbles
carries one scale, every head attends to its own slice of the past, and the ring
rotates until each shard has seen every key. Repeat the story until the cache is full:"

run() {
  python -m distributed_llama_tpu.apps.dllama inference \
    --model "$MODEL" --tokenizer "$TOKENIZER" \
    --prompt "$PROMPT" --steps "$STEPS" --temperature 0 --seed 12345 "$@" \
    | grep -v '^🔶\|^⏩\|^💡\|^🔷\|^Columns\|^S/R\|tokens\|time:\|^Weight stream' || true
}

OUT1=$(run)
OUT2=$(run)

if [ "$OUT1" != "$OUT2" ]; then
  echo "❌ DETERMINISM FAILURE: two identical runs disagreed"
  diff <(echo "$OUT1") <(echo "$OUT2") || true
  exit 1
fi
echo "✅ determinism: two runs produced identical output ($STEPS greedy tokens)"

EXPECTED="examples/determinism_expected_cpu.txt"
if [ -z "$DLLAMA_MODEL" ] && [ "${JAX_PLATFORMS:-}" = "cpu" ] && [ -f "$EXPECTED" ]; then
  if [ "$OUT1" == "$(cat "$EXPECTED")" ]; then
    echo "✅ determinism: output matches the committed CPU expectation"
  else
    echo "❌ output differs from $EXPECTED"
    diff <(echo "$OUT1") "$EXPECTED" || true
    exit 1
  fi
fi
