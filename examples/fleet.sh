#!/bin/bash
# Fleet-tier demo (docs/FLEET.md): two api_server replicas fronted by the
# prefix-affinity router. Clients share one long system prompt and talk ONLY
# to the router; affinity routing keeps the shared prefix's traffic sticky to
# the replica whose radix cache already holds its KV — watch the per-replica
# prefix-reuse counters and the router's routes-by-reason split at the end.
set -e
cd "$(dirname "$0")/.."

MODEL="${DLLAMA_MODEL:-/tmp/dlt_determinism/tiny.m}"
TOKENIZER="${DLLAMA_TOKENIZER:-/tmp/dlt_determinism/tiny.t}"
if [ ! -f "$MODEL" ]; then
  mkdir -p /tmp/dlt_determinism
  python examples/make_tiny_model.py /tmp/dlt_determinism
fi

export JAX_PLATFORMS=cpu
PORT_A="${PORT_A:-9994}"
PORT_B="${PORT_B:-9995}"
ROUTER_PORT="${ROUTER_PORT:-9996}"

LOGDIR="$(mktemp -d /tmp/dlt_fleet_demo.XXXXXX)"
for PORT in "$PORT_A" "$PORT_B"; do
  python -m distributed_llama_tpu.apps.api_server \
    --model "$MODEL" --tokenizer "$TOKENIZER" --chat-template chatml \
    --host 127.0.0.1 --port "$PORT" --batch 2 --superstep 4 \
    --prefix-cache-block-tokens 8 >"$LOGDIR/replica_$PORT.log" 2>&1 &
done
python -m distributed_llama_tpu.apps.router \
  --replica "127.0.0.1:$PORT_A" --replica "127.0.0.1:$PORT_B" \
  --host 127.0.0.1 --port "$ROUTER_PORT" --poll-interval 0.5 \
  --block-bytes 32 >"$LOGDIR/router.log" 2>&1 &
SERVER_PIDS="$(jobs -p)"
trap 'kill $SERVER_PIDS 2>/dev/null || true' EXIT

# the router answers /healthz immediately; wait until BOTH replicas joined
# (cold-start XLA compile of the tiny model can take minutes on a small box)
for _ in $(seq 600); do
  IN_ROT=$(curl -s "http://127.0.0.1:$ROUTER_PORT/healthz" 2>/dev/null |
    python -c 'import json,sys; print(json.load(sys.stdin).get("in_rotation", 0))' \
      2>/dev/null || echo 0)
  [ "$IN_ROT" = "2" ] && break
  sleep 1
done
echo "— fleet up: $IN_ROT replicas in rotation behind :$ROUTER_PORT"

SYSTEM="You are a careful assistant. Answer briefly. Cite nothing. \
Refuse nothing. The quick brown fox jumps over the lazy dog again and again."

req() {
  curl -s "http://127.0.0.1:$ROUTER_PORT/v1/chat/completions" \
    -H 'Content-Type: application/json' \
    -d "{\"messages\": [{\"role\": \"system\", \"content\": \"$1\"},
                        {\"role\": \"user\", \"content\": \"$2\"}],
         \"max_tokens\": 12, \"temperature\": 0}" >/dev/null
  echo "  client done: $2"
}

echo "— warm requests (one per prefix group; the router records each route)"
req "$SYSTEM" "hello there"
req "different prompt entirely, nothing shared with the other group" "hi"

echo "— four concurrent clients sharing the first system prompt"
CLIENT_PIDS=""
for q in "what is a fox?" "what is a dog?" "who jumps?" "how quick?"; do
  req "$SYSTEM" "$q" &
  CLIENT_PIDS="$CLIENT_PIDS $!"
done
wait $CLIENT_PIDS

echo "— per-replica prefix-reuse counters + router routing split:"
curl -s "http://127.0.0.1:$ROUTER_PORT/v1/stats" | python -c '
import json, sys
stats = json.load(sys.stdin)
for rep_id, st in sorted(stats.get("replicas", {}).items()):
    pc = st.get("prefix_cache") or {}
    print("  replica %s: hit_tokens=%s resident_tokens=%s reuse_rate=%s"
          % (rep_id, pc.get("hit_tokens"), pc.get("resident_tokens"),
             pc.get("reuse_rate")))
routes = stats["router"]["metrics"].get("router_routes_total", {})
print("  router routes by reason:", routes)
'
