#!/bin/bash
# Long-context sequence parallelism demo on the 8-device virtual CPU mesh:
# the KV cache shards over sp=4 (each device holds seq_len/4 positions in the
# STRIPED deferred layout), ring attention rotates only the live-context window
# per decode step, and tp=2 shards heads orthogonally. This is the TPU-native
# answer to the reference's --kv-cache-storage disc out-of-core valve (see
# README "Long context / memory"); the same command runs unchanged on a real
# TPU mesh.
#
#   bash examples/long-context-sp.sh <model.m> <tokenizer.t> [prompt]
set -e
MODEL="$(realpath "${1:?usage: long-context-sp.sh model.m tokenizer.t [prompt]}")"
TOK="$(realpath "${2:?usage: long-context-sp.sh model.m tokenizer.t [prompt]}")"
PROMPT="${3:-Once upon a time}"
cd "$(dirname "$0")/.."

JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
python -m distributed_llama_tpu.apps.dllama generate \
  --model "$MODEL" --tokenizer "$TOK" \
  --prompt "$PROMPT" --steps 48 --temperature 0 \
  --tp 2 --sp 4
