#!/usr/bin/env python
"""Minimal OpenAI-compatible client for the dllama-api server — the counterpart of the
reference's examples/chat-api-client.js (same endpoint, same request shape; Python
because this image carries no Node runtime).

Usage:
  1. Start the server:
       python -m distributed_llama_tpu.apps.api_server --model m.m --tokenizer t.t --port 9990
  2. Run this script:
       python examples/chat-api-client.py            # non-streaming
       python examples/chat-api-client.py --stream   # SSE streaming

HOST/PORT env vars override the default 127.0.0.1:9990.
"""

import argparse
import json
import os
import urllib.request

HOST = os.environ.get("HOST", "127.0.0.1")
PORT = int(os.environ.get("PORT", "9990"))
URL = f"http://{HOST}:{PORT}/v1/chat/completions"


def chat(messages, max_tokens=64, stream=False, temperature=0.7):
    body = json.dumps({
        "messages": messages,
        "temperature": temperature,
        "max_tokens": max_tokens,
        "stream": stream,
    }).encode()
    req = urllib.request.Request(
        URL, data=body, headers={"Content-Type": "application/json"})
    resp = urllib.request.urlopen(req)
    if not stream:
        return json.loads(resp.read())["choices"][0]["message"]["content"]
    # SSE: one `data: {...}` chunk per token, terminated by `data: [DONE]`
    text = []
    for raw in resp:
        line = raw.decode().strip()
        if not line.startswith("data:"):
            continue
        payload = line[5:].strip()
        if payload == "[DONE]":
            break
        delta = json.loads(payload)["choices"][0]["delta"]
        piece = delta.get("content", "")
        print(piece, end="", flush=True)
        text.append(piece)
    print()
    return "".join(text)


def ask(system, user, max_tokens, stream):
    print(f"> system: {system}")
    print(f"> user: {user}")
    messages = [{"role": "system", "content": system},
                {"role": "user", "content": user}]
    out = chat(messages, max_tokens=max_tokens, stream=stream)
    if not stream:
        print(out)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--stream", action="store_true")
    ap.add_argument("--max-tokens", type=int, default=64)
    args = ap.parse_args()
    ask("You are an excellent math teacher.", "What is 1 + 2?",
        args.max_tokens, args.stream)
    ask("You are a helpful assistant.", "Say hello.", args.max_tokens, args.stream)


if __name__ == "__main__":
    main()
