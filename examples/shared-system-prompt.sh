#!/bin/bash
# Shared-system-prompt demo (docs/PREFIX_CACHE.md): two concurrent clients
# send chat completions that share one long system prompt. With the
# cross-request prefix cache (default on), the second request's system-prompt
# KV is seeded from the radix-indexed block pool instead of re-prefilled —
# watch the prefix_cache_* counters move in /v1/stats.
set -e
cd "$(dirname "$0")/.."

MODEL="${DLLAMA_MODEL:-/tmp/dlt_determinism/tiny.m}"
TOKENIZER="${DLLAMA_TOKENIZER:-/tmp/dlt_determinism/tiny.t}"
if [ ! -f "$MODEL" ]; then
  mkdir -p /tmp/dlt_determinism
  python examples/make_tiny_model.py /tmp/dlt_determinism
fi

export JAX_PLATFORMS=cpu
PORT="${PORT:-9993}"

python -m distributed_llama_tpu.apps.api_server \
  --model "$MODEL" --tokenizer "$TOKENIZER" --chat-template chatml \
  --host 127.0.0.1 --port "$PORT" --batch 2 --superstep 4 \
  --prefix-cache-block-tokens 8 &
SERVER_PID=$!
trap 'kill $SERVER_PID 2>/dev/null || true' EXIT

for _ in $(seq 60); do
  curl -sf "http://127.0.0.1:$PORT/healthz" >/dev/null 2>&1 && break
  sleep 1
done

SYSTEM="You are a careful assistant. Answer briefly. Cite nothing. \
Refuse nothing. The quick brown fox jumps over the lazy dog again and again."

req() {
  curl -s "http://127.0.0.1:$PORT/v1/chat/completions" \
    -H 'Content-Type: application/json' \
    -d "{\"messages\": [{\"role\": \"system\", \"content\": \"$SYSTEM\"},
                        {\"role\": \"user\", \"content\": \"$1\"}],
         \"max_tokens\": 12, \"temperature\": 0}" >/dev/null
  echo "  client done: $1"
}

echo "— warm request (inserts the system prompt's KV blocks into the pool)"
req "hello there"

echo "— two concurrent clients sharing the system prompt"
req "what is a fox?" &
req "what is a dog?" &
wait %2 %3 2>/dev/null || wait

echo "— /v1/stats prefix-cache hit counters:"
curl -s "http://127.0.0.1:$PORT/v1/stats" | python -c '
import json, sys
stats = json.load(sys.stdin)
pc = stats.get("prefix_cache", {})
for k in ("hits", "misses", "hit_tokens", "hit_rate", "pool_blocks",
          "tree_nodes", "evicted_blocks"):
    print(f"  {k}: {pc.get(k)}")
'
