#!/bin/bash
# Multi-device demo without TPU hardware — the counterpart of the reference's
# examples/n-workers.sh (which screens N worker processes on localhost ports).
#
# Under SPMD there are no worker processes to spawn: the same program runs on every
# mesh device and XLA lowers the psum/all_gather merge points to collectives. This
# demo fakes an 8-chip host with XLA's virtual CPU devices and runs 4-way tensor
# parallel x 2-way sequence parallel (ring attention) inference.
set -e
cd "$(dirname "$0")/.."

MODEL="${DLLAMA_MODEL:-/tmp/dlt_determinism/tiny.m}"
TOKENIZER="${DLLAMA_TOKENIZER:-/tmp/dlt_determinism/tiny.t}"
if [ ! -f "$MODEL" ]; then
  mkdir -p /tmp/dlt_determinism
  python examples/make_tiny_model.py /tmp/dlt_determinism
fi

export JAX_PLATFORMS=cpu
export XLA_FLAGS="--xla_force_host_platform_device_count=8 ${XLA_FLAGS:-}"

python -m distributed_llama_tpu.apps.dllama inference \
  --model "$MODEL" --tokenizer "$TOKENIZER" \
  --prompt "Eight devices, one program:" --steps 24 --temperature 0 \
  --tp 4 --sp 2
